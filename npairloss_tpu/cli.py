"""Command-line driver — the ``caffe train --solver=...`` counterpart.

The reference is launched as ``caffe train --solver=usage/solver.prototxt``
(SURVEY.md §3.1) under mpirun.  Here the same entrypoint is

    python -m npairloss_tpu train --solver usage/solver.prototxt

which parses the solver + net prototxts through the config front-end,
builds the embedding model and identity-balanced data iterators, and runs
the Solver loop on whatever accelerator JAX sees — multi-chip via
``--mesh`` (all devices by default) with the negative pool all-gathered
across the mesh in-graph.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import Optional

log = logging.getLogger("npairloss_tpu.cli")

# The --precision vocabulary, hardcoded rather than imported: argparse
# construction must stay jax-free (the bench parent contract — a hung
# backend import in the parser would defeat bench.py's no-jax-in-parent
# robustness).  Pinned == models.precision.available_policies() by
# tests/test_precision_policy.py, so drift is a test failure.
_PRECISION_CHOICES = ("bf16", "fp32_parity", "mxu")

# The staticcheck pass vocabulary, hardcoded for the same reason
# (analysis itself is stdlib-only, but the parser stays literal).
# Pinned == analysis.runner.PASS_NAMES by tests/test_staticcheck.py.
_STATICCHECK_PASSES = ("purity", "scopes", "locks", "contracts",
                       "vocab", "markers")

# The --probe-impl vocabulary, hardcoded for the same jax-free-parser
# reason.  Pinned == ops.pallas_ivf.PROBE_IMPLS by the staticcheck
# vocab pass AND tests/test_pallas_ivf.py, so drift is a lint failure.
_PROBE_IMPL_CHOICES = ("scan", "fused", "auto")


def _identity_batch_geometry(d):
    """(identities, images-per-identity) per batch from a MultibatchData
    layer cfg; the flagship 60x2 geometry (def.prototxt:25-27) when the
    layer is absent."""
    if d is None:
        return 60, 2
    ids = d.identity_num_per_batch or max(2, (d.batch_size or 8) // 2)
    imgs = d.img_num_per_identity or 2
    return ids, imgs


def _build_data(net_cfg, phase: str, input_shape, seed: int = 0,
                synthetic: bool = False, native: str = "auto"):
    """Batches for a phase: the real MultibatchData pipeline from the
    net's source list file, or synthetic identity-balanced clusters when
    ``--synthetic`` was passed explicitly.

    A missing/unreadable source is a hard error unless --synthetic: a
    typo'd path must never silently "train" on random clusters.
    """
    d = net_cfg.data.get(phase)
    if d is None:
        return None, None
    if not synthetic:
        if not d.source:
            raise SystemExit(
                f"{phase} data layer has no `source` list file; pass "
                "--synthetic to train on synthetic identity clusters"
            )
        if not os.path.exists(d.source):
            raise SystemExit(
                f"{phase} data source {d.source!r} does not exist; fix the "
                "net prototxt or pass --synthetic for synthetic data"
            )
        from npairloss_tpu.data import multibatch_loader

        return (
            multibatch_loader(d, net_cfg.transformer, seed=seed,
                              native=native),
            d,
        )
    from npairloss_tpu.data import synthetic_identity_batches

    ids, imgs = _identity_batch_geometry(d)
    return (
        synthetic_identity_batches(
            ids * 4, ids, imgs, input_shape, seed=seed
        ),
        d,
    )


def _pos_topk_arg(v: str):
    """argparse type for --pos-topk: 'auto' or a non-negative int."""
    if v == "auto":
        return "auto"
    try:
        k = int(v)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected 'auto' or a non-negative integer, got {v!r}")
    if k < 0:
        raise argparse.ArgumentTypeError(
            f"buffer slots must be >= 0, got {k}")
    return k


def _build_solver(args):
    """Shared setup for train/test/extract: parse the solver + net
    prototxts, build the model and (optional) mesh, restore a snapshot.
    Returns (solver, net_cfg, input_shape) or an int error code."""
    import jax

    from npairloss_tpu.config import load_net, load_solver
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    if getattr(args, "solver", None):
        solver_cfg, net_path = load_solver(args.solver)
    else:
        # ``time`` needs only a net, like ``caffe time -model X``; solver
        # hyperparameters are irrelevant to a timing run.
        solver_cfg, net_path = SolverConfig(), None
    if args.net:
        net_path = args.net
    elif net_path and not os.path.isabs(net_path):
        # Caffe resolves the net path relative to the CWD; fall back to
        # solver-relative when that misses (the shipped solver points at
        # a machine-specific ./conf_same_veri/ path).
        if not os.path.exists(net_path):
            cand = os.path.join(os.path.dirname(args.solver), net_path)
            net_path = cand if os.path.exists(cand) else net_path
    if not net_path or not os.path.exists(net_path):
        log.error("net prototxt not found (tried %r); pass --net", net_path)
        return 2
    net_cfg = load_net(net_path)

    if getattr(args, "max_iter", None) is not None:
        import dataclasses

        solver_cfg = dataclasses.replace(solver_cfg, max_iter=args.max_iter)
    if getattr(args, "snapshot_prefix", None):
        import dataclasses

        solver_cfg = dataclasses.replace(
            solver_cfg, snapshot_prefix=args.snapshot_prefix
        )
    if getattr(args, "snapshot_keep", None) is not None:
        import dataclasses

        solver_cfg = dataclasses.replace(
            solver_cfg, snapshot_max_keep=args.snapshot_keep
        )
    if getattr(args, "pipeline", False):
        import dataclasses

        solver_cfg = dataclasses.replace(
            solver_cfg,
            pipeline=True,
            pipeline_depth=getattr(args, "pipeline_depth", 2) or 2,
            pipeline_window=getattr(args, "pipeline_window", 0) or 0,
        )
    if getattr(args, "compile_cache", None):
        import dataclasses

        solver_cfg = dataclasses.replace(
            solver_cfg, compile_cache=args.compile_cache
        )
        # Enable NOW, before any jit below compiles (snapshot restore,
        # weight conversion) — the cache must cover every program this
        # process builds, not just the train step.
        from npairloss_tpu.pipeline import enable_compile_cache

        enable_compile_cache(args.compile_cache)

    crop = 0
    # Shape from the TRAIN layer, else the TEST layer (a net may define
    # only one; test/extract against a TEST-only net must not default).
    for phase in ("TRAIN", "TEST"):
        d = net_cfg.data.get(phase)
        if d is not None and d.transform.crop_size:
            crop = d.transform.crop_size
            break
    side = crop or 224
    input_shape = (side, side, 3)

    loss_cfg = net_cfg.loss.loss if net_cfg.loss else None
    if loss_cfg is None:
        from npairloss_tpu.ops.npair_loss import NPairLossConfig

        loss_cfg = NPairLossConfig()

    mesh = None
    n_dev = len(jax.devices())
    engine = getattr(args, "engine", None)
    want = args.mesh if args.mesh is not None else (n_dev if n_dev > 1 else 1)
    if engine == "blockwise" and args.mesh is None:
        # The Pallas blockwise engine is the single-device streaming
        # path; don't auto-build a mesh around it.  An EXPLICIT --mesh
        # still reaches the Solver's blockwise+mesh contradiction error.
        want = 1
    mp = int(getattr(args, "mp", 1) or 1)
    if want > 1 or engine == "ring" or mp > 1:
        # Ring streams over a mesh axis; a 1-device mesh is valid (the
        # bench times it), so honor --engine ring even single-device.
        # --mp > 1 folds the same devices into a 2-D dp x mp mesh for
        # partition rules that shard parameters (docs/DISTRIBUTED.md).
        from npairloss_tpu.parallel import build_mesh

        mesh = build_mesh(jax.devices()[:max(want, 1)], mp=mp)
    elif engine == "auto":
        # Nothing to exchange on a single shard: auto degrades to the
        # default engine without wrapping a 1-device shard_map mesh
        # around the step.
        engine = None

    partition_rules = None
    if getattr(args, "partition_rules", None):
        from npairloss_tpu.parallel import load_partition_rules
        from npairloss_tpu.parallel.partition import PartitionRuleError

        try:
            partition_rules = load_partition_rules(args.partition_rules)
        except (OSError, ValueError, PartitionRuleError) as e:
            log.error("--partition-rules %s: %s", args.partition_rules, e)
            return 2
        if mesh is None:
            # The module's loud-by-design contract extends to the CLI:
            # a sharding table on a mesh-less run would silently never
            # apply — exactly the no-op shape the table exists to kill.
            log.error("--partition-rules given but no mesh was built "
                      "(single device, no --mesh/--mp): the table "
                      "would silently not apply")
            return 2

    model_name = args.model or _model_for_net(net_cfg)
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model_kw = {}
    if getattr(args, "remat", False):
        model_kw["remat"] = True  # GoogLeNet trunks; others raise loudly
    if getattr(args, "caffe_pad", False):
        model_kw["caffe_pad"] = True  # GoogLeNet trunks
    precision = getattr(args, "precision", None)
    if precision:
        # Declarative mixed-precision policy (models.precision):
        # resolves the trunk's dtypes AND the loss engines' gemm
        # precision (below) from one named recipe; --bf16 is the
        # legacy spelling of what --precision bf16 now names.
        model = get_model(model_name, policy=precision, **model_kw)
    else:
        model = get_model(model_name, dtype=dtype, **model_kw)

    engine_plan = None
    if mesh is not None and engine != "blockwise":
        # DCN-aware engine selection (parallel.plan): consult the
        # roofline interconnect peaks + the mesh's host topology;
        # --engine auto takes the plan's choice, an explicit engine is
        # honored but the plan (with what auto would have said) is
        # still stamped into the run manifest as provenance.
        from npairloss_tpu.parallel import plan_for_mesh

        d_any = net_cfg.data.get("TRAIN") or net_cfg.data.get("TEST")
        ids, imgs = _identity_batch_geometry(d_any)
        emb_dim = int(getattr(model, "embedding_dim", 0) or 512)
        from npairloss_tpu.obs.fleet.stamp import resolved_process

        engine_plan = plan_for_mesh(
            mesh, ids * imgs, emb_dim,
            requested=engine if engine else "dense",
            process_count=resolved_process()[1],
        )
        if engine == "auto":
            engine = engine_plan.engine
            log.info("engine auto -> %s over %s (%s)",
                     engine, engine_plan.link, engine_plan.reason)

    sim_cache = getattr(args, "sim_cache", None)
    pos_topk = getattr(args, "pos_topk", None)
    solver = Solver(
        model, loss_cfg, solver_cfg, mesh=mesh, input_shape=input_shape,
        engine=engine,
        partition_rules=partition_rules,
        sim_cache={"auto": None, "on": True, "off": False}[sim_cache or "auto"],
        pos_topk=None if pos_topk in (None, "auto") else int(pos_topk),
        matmul_precision=getattr(args, "matmul_precision", None),
        precision=precision or None,
        param_mults=net_cfg.param_mults,
        loss_weight=(net_cfg.loss.loss_weights[0]
                     if net_cfg.loss and net_cfg.loss.loss_weights
                     else 1.0),
    )
    solver.engine_plan = engine_plan
    if getattr(args, "resume", None):
        if args.resume == "auto":
            # Auto-resume (docs/RESILIENCE.md): newest manifest-valid
            # snapshot under snapshot_prefix, torn/corrupt ones skipped
            # with a logged reason; none found = fresh start (the
            # supervisor-relaunch contract — first launch and relaunch
            # run the same command line).
            restored = solver.restore_auto()
            if restored:
                log.info("auto-resume: %s (iteration %d)",
                         restored, solver.iteration)
        else:
            solver.restore_snapshot(args.resume)
    elif getattr(args, "weights", None):
        _load_weights_into(solver, args.weights)
    return solver, net_cfg, input_shape


def cmd_train(args) -> int:
    if getattr(args, "metrics_port", None) and \
            not getattr(args, "live_obs", False):
        # The exporter serves the live registry; without --live-obs
        # there is none — refuse up front rather than train for hours
        # while the scraper gets connection-refused.
        log.error("--metrics-port needs --live-obs (there is no "
                  "metric registry to export without it)")
        return 2
    if getattr(args, "remediation_config", None):
        # Parse NOW: a typo'd policy table must not cost a solver
        # build + restore first (it re-loads cheaply at wiring time).
        from npairloss_tpu.resilience.remediate import load_policies

        try:
            load_policies(args.remediation_config)
        except (OSError, ValueError) as e:
            log.error("--remediation-config %s: %s",
                      args.remediation_config, e)
            return 2
    # The MPI_COMM_WORLD replacement: must run before the first backend
    # query (exactly as MPI_Init precedes any communicator use).
    from npairloss_tpu.parallel import initialize_distributed

    initialize_distributed(
        args.coordinator, args.num_processes, args.process_id
    )

    if getattr(args, "caffe_solverstate", None):
        # Checked BEFORE _build_solver, which eagerly restores --resume.
        if getattr(args, "resume", None):
            log.error("--caffe-solverstate conflicts with --resume "
                      "(pick the Caffe snapshot or the Orbax one)")
            return 2
        if not getattr(args, "weights", None):
            # `caffe train --snapshot` restores the paired .caffemodel
            # automatically; here the weights arrive separately — a
            # solverstate on top of RANDOM init would be a silently
            # corrupt resume (50k-step momentum, fresh weights).
            log.error(
                "--caffe-solverstate needs --weights (the paired "
                ".caffemodel, converted via import-caffemodel) — "
                "resuming momentum over random-init weights would be "
                "a corrupt trajectory")
            return 2

    built = _build_solver(args)
    if isinstance(built, int):
        return built
    solver, net_cfg, input_shape = built

    if net_cfg.param_mults_conflict:
        # Parse records (rather than raises) conflicting per-layer
        # param recipes so inference-only commands can still load the
        # net; training would silently apply NO multipliers, so it is
        # the one path that must refuse.
        log.error("%s", net_cfg.param_mults_conflict)
        return 2

    if getattr(args, "caffe_solverstate", None):
        # The `caffe train --snapshot X.solverstate` semantics: resume
        # the optimizer (momentum + iteration) from a Caffe snapshot;
        # weights come from the paired .caffemodel via --weights.
        try:
            it = solver.load_caffe_solverstate(
                args.caffe_solverstate,
                args.model or _model_for_net(net_cfg),
            )
        except NotImplementedError as e:
            log.error("%s", e)
            return 2
        log.info("resumed optimizer from %s at iteration %d",
                 args.caffe_solverstate, it)

    if getattr(args, "dump_partitions", False):
        # Preflight visibility (docs/DISTRIBUTED.md): the resolved
        # rule -> PartitionSpec table per state leaf, with per-rule
        # match counts — a silent no-op rule (0 matches) is visible
        # BEFORE a multi-hour run.  Pair with --max_iter 0 for a
        # check-only invocation.  Mesh-less runs have no placement to
        # resolve, so the flag demands one.
        if solver.mesh is None:
            log.error("--dump-partitions needs a mesh "
                      "(--mesh/--mp): single-device runs have no "
                      "placement to resolve")
            return 2
        from npairloss_tpu.parallel import render_partition_table

        print(render_partition_table(solver.partition_table()),
              flush=True)

    train_iter, _ = _build_data(
        net_cfg, "TRAIN", input_shape, seed=0, synthetic=args.synthetic,
        native=args.native,
    )
    test_iter, _ = _build_data(
        net_cfg, "TEST", input_shape, seed=1, synthetic=args.synthetic,
        native=args.native,
    )
    if train_iter is None:
        log.error(
            "net %s has no TRAIN MultibatchData layer",
            args.net or args.solver,
        )
        return 2

    import jax as _jax

    if _jax.process_count() > 1:
        # Multi-controller data model (docs/DISTRIBUTED.md): every
        # controller builds the same deterministic loader; each takes
        # its process-disjoint row shard of every global batch, and
        # Solver._put_batch reassembles them in process order into the
        # pod-global array — the mpirun per-rank MultibatchData shape,
        # with global batch = sum of the local batches.
        from npairloss_tpu.data import shard_batches

        train_iter = shard_batches(
            train_iter, _jax.process_index(), _jax.process_count())
        if test_iter is not None:
            test_iter = shard_batches(
                test_iter, _jax.process_index(), _jax.process_count())

    # Configure logging only when the embedder has not.  basicConfig is
    # already a no-op when the ROOT logger has handlers; the extra check
    # covers embedders that configured the package logger directly
    # (handlers beyond our NullHandler) without touching root — adding a
    # root handler there would double their output.
    _pkg_handlers = [
        h for h in logging.getLogger("npairloss_tpu").handlers
        if not isinstance(h, logging.NullHandler)
    ]
    if not logging.getLogger().handlers and not _pkg_handlers:
        logging.basicConfig(level=logging.INFO, format="%(message)s")

    if getattr(args, "debug_checks", False):
        from npairloss_tpu.utils.debug import enable_debug_checks

        enable_debug_checks(True)
    if getattr(args, "health_metrics", False) or \
            getattr(args, "mining_health", False):
        from npairloss_tpu.obs import HealthConfig

        # --mining-health implies the health rows it extends: the
        # AP/AN margin + saturation stats ride the same loss aux.
        solver.health = HealthConfig(
            mining_health=bool(getattr(args, "mining_health", False)))
    if getattr(args, "perf_metrics", False):
        # Continuous phase="perf" rows (ms_per_step / emb_per_sec /
        # MFU) at display cadence — docs/OBSERVABILITY.md §Perf.
        solver.perf_metrics = True

    from npairloss_tpu.resilience import (
        EXIT_PREEMPTED,
        DivergenceConfig,
        DivergenceError,
        PreemptionSignal,
        TrainingPreempted,
    )

    if getattr(args, "divergence_patience", 0):
        solver.divergence = DivergenceConfig(
            patience=args.divergence_patience,
            action=args.divergence_action,
            lr_scale=args.divergence_lr_scale,
            max_rollbacks=args.divergence_max_rollbacks,
        )

    # Graceful preemption (docs/RESILIENCE.md): SIGTERM/SIGINT finish
    # the in-flight step, commit an emergency snapshot, flush telemetry,
    # and exit EXIT_PREEMPTED so a supervisor relaunches with
    # ``--resume auto``.  install() no-ops off the main thread.
    preempt = None
    if not getattr(args, "no_preempt_handler", False):
        preempt = PreemptionSignal().install()
        solver.preempt = preempt

    telemetry = None
    live = None
    exporter = None
    tel_dir = getattr(args, "telemetry_dir", None)
    trace_dir = getattr(args, "trace_dir", None)
    record_fn, log_file = None, None
    try:
        if getattr(args, "live_obs", False):
            # Live observatory (docs/OBSERVABILITY.md §Live): watchdog
            # SLOs over the run's own telemetry rows, alerts.jsonl in
            # the run dir, optional /metrics on --metrics-port.
            if not tel_dir:
                log.error("--live-obs needs --telemetry-dir (the "
                          "registry is fed by the run's metric rows)")
                return 2
            from npairloss_tpu.obs.live import (
                LiveObservatory,
                default_watchdogs,
                load_slo_config,
            )

            if getattr(args, "slo_config", None):
                specs = load_slo_config(args.slo_config)
            else:
                specs = default_watchdogs("train")
            live = LiveObservatory(specs, out_dir=tel_dir)

            def _snapshot_age_probe():
                # Newest committed snapshot's manifest age — state the
                # process already has on disk, polled per tick.
                from npairloss_tpu.resilience.snapshot import (
                    list_snapshots,
                )
                from npairloss_tpu.train import snapshot_info

                snaps = list_snapshots(solver.cfg.snapshot_prefix)
                if not snaps:
                    return
                created = snapshot_info(snaps[-1][1])["created"]
                if created is not None:
                    import time as _time

                    live.registry.set("train_snapshot_age_s",
                                      max(_time.time() - created, 0.0))

            live.add_probe(_snapshot_age_probe)
            if getattr(args, "remediate_dry_run", False):
                args.remediate = True  # a dry-run IS a remediation run
            if getattr(args, "remediate", False):
                # Alert→actuation for training (docs/RESILIENCE.md
                # §Remediation): a health-signal alert (embedding
                # collapse) requests a rollback the train loop executes
                # at its next safe point — resilience/guard.py's
                # divergence recovery generalized beyond non-finite
                # streaks.
                from npairloss_tpu.resilience.guard import (
                    RollbackRequest,
                )
                from npairloss_tpu.resilience.remediate import (
                    RemediationEngine,
                    default_policies,
                    load_policies,
                )

                def _rollback_action(alert):
                    solver.request_rollback(RollbackRequest(
                        reason=(f"{alert.get('slo')} alert "
                                f"{alert.get('alert_id')}"),
                        before_wall_time=alert.get("fired_at"),
                    ))
                    return {"requested": True}

                policies = (
                    load_policies(args.remediation_config)
                    if getattr(args, "remediation_config", None)
                    else default_policies("train"))
                try:
                    remediation = RemediationEngine(
                        policies,
                        {"trainer_rollback": _rollback_action},
                        log_path=os.path.join(tel_dir,
                                              "remediation.jsonl"),
                        dry_run=getattr(args, "remediate_dry_run",
                                        False),
                    )
                except ValueError as e:
                    # A config naming an action training cannot perform
                    # is a config error, not a crash.
                    log.error("--remediation-config %s: %s",
                              args.remediation_config, e)
                    return 2
                live.set_remediation(remediation)
                log.info(
                    "remediation armed: %s%s",
                    ", ".join(f"{p.name}({p.slo}->{p.action})"
                              for p in policies),
                    " [DRY-RUN]" if remediation.dry_run else "")
        elif getattr(args, "remediate", False) or \
                getattr(args, "remediate_dry_run", False):
            log.error("--remediate needs --live-obs (remediation is "
                      "driven by the alert engine)")
            return 2
        if tel_dir or trace_dir:
            import dataclasses

            import jax

            from npairloss_tpu.obs.fleet import fleet_stamp

            # Fleet stamping (docs/OBSERVABILITY.md §Fleet): automatic
            # for multi-process runs (EVERY rank writes its own
            # telemetry.r<k>.jsonl — the old rank-0 gate threw away
            # exactly the streams straggler analysis needs), forceable
            # with --fleet on a single-host mesh.  Off (the byte-
            # identical legacy layout, rank 0 only) otherwise.
            stamp = fleet_stamp()
            fleet_on = bool(getattr(args, "fleet", False)) or (
                stamp is not None and stamp.process_count > 1
            )
            if fleet_on or jax.process_index() == 0:
                from npairloss_tpu.obs import RunTelemetry

                # --telemetry-dir = the full run directory (manifest +
                # metrics.jsonl + trace.json); --trace-dir alone = span
                # tracing only (trace.json, no metric rows).  argparse
                # makes them mutually exclusive.
                telemetry = RunTelemetry(
                    tel_dir or trace_dir, metrics=bool(tel_dir),
                    fleet=fleet_on,
                    extra_sinks=(live.sink,) if live is not None else (),
                )
                if tel_dir:
                    from npairloss_tpu.parallel import mesh_topology

                    telemetry.write_manifest(
                        config={
                            "solver": dataclasses.asdict(solver.cfg),
                            "loss": dataclasses.asdict(solver.loss_cfg),
                            "model": args.model or _model_for_net(net_cfg),
                            "net": args.net,
                            "engine": solver.engine,
                            "synthetic": bool(args.synthetic),
                            "health_metrics":
                                bool(getattr(args, "health_metrics", False)),
                            # Pod-scale provenance (docs/DISTRIBUTED.md):
                            # WHY this engine (DCN-aware plan) and WHERE
                            # every state leaf lives (rule digest, with
                            # zero-match rules flagged).
                            "engine_plan": (
                                solver.engine_plan.to_dict()
                                if solver.engine_plan is not None else None
                            ),
                            "partition": (
                                solver.partition_summary()
                                if solver.mesh is not None else None
                            ),
                        },
                        mesh=(
                            mesh_topology(solver.mesh, solver.axis)
                            if solver.mesh is not None else None
                        ),
                    )
                solver.telemetry = telemetry

        if getattr(args, "log_json", None):
            import jax

            # Rank-gate: in a multi-process run, N hosts appending to one
            # shared path would duplicate every event N times.
            if jax.process_index() == 0:
                from npairloss_tpu.obs import JsonlSink

                # The obs sink IS this format (append JSONL, line
                # buffered) — one implementation to maintain.  Records
                # pass through verbatim: --log-json predates the
                # run-telemetry envelope and its consumers key on the
                # solver's {"event", "iteration"} fields.
                log_file = JsonlSink(args.log_json)
                record_fn = log_file.log

        if live is not None:
            live.start(period_s=args.slo_tick)
            if getattr(args, "metrics_port", None):
                from npairloss_tpu.obs.live import start_http_exporter

                # Train has no HTTP surface of its own — an opt-in
                # localhost exporter serves /metrics (+ /healthz with
                # SLO status) for scrapers.
                exporter = start_http_exporter(
                    live.registry, args.metrics_port,
                    health_fn=lambda: {"ok": True, **live.health()},
                )

        # max_iter override was already baked into solver.cfg by
        # _build_solver; train() falls back to it — one source of truth.
        preempted = None
        try:
            final = solver.train(
                train_iter,
                test_batches=test_iter,
                log_fn=lambda s: print(s, flush=True),
                record_fn=record_fn,
            )
        except TrainingPreempted as e:
            # The emergency snapshot already landed (Solver.train commits
            # it before raising); exit the supervisor-relaunch code.
            preempted = e
        except DivergenceError as e:
            log.error("%s", e)
            return 1
    finally:
        # Telemetry closes on EVERY exit path so a crashed run still
        # leaves metrics.jsonl/trace.json on disk (the diagnosable-from-
        # artifacts contract, docs/OBSERVABILITY.md).  Both closes are
        # guarded: a disk-full close failure is reported but must
        # neither skip the other close nor mask the train outcome
        # propagating past this finally.
        if preempt is not None:
            preempt.uninstall()
        if exporter is not None:
            try:
                exporter.shutdown()
                exporter.server_close()
            except Exception as e:
                log.error("metrics exporter shutdown failed: %s", e)
        if live is not None:
            try:
                live.stop()  # final tick lands pending alert transitions
            except Exception as e:
                log.error("live-obs stop failed: %s", e)
        if log_file is not None:
            try:
                log_file.close()
            except Exception as e:
                log.error("--log-json close failed: %s", e)
        if telemetry is not None:
            try:
                telemetry.close()
            except Exception as e:
                log.error("telemetry close failed: %s", e)
    if preempted is not None:
        print(json.dumps({
            "preempted": True,
            "iteration": preempted.step,
            "snapshot": preempted.snapshot_path,
            "resume": "--resume auto",
        }))
        return EXIT_PREEMPTED
    print(json.dumps({k: float(v) for k, v in final.items()}))
    return 0


def _model_for_net(net_cfg) -> str:
    name = (net_cfg.name or "").lower().replace(" ", "")
    if "resnet" in name:
        return "resnet50"
    if "vit" in name:
        return "vit_b16"
    if "mlp" in name:
        return "mlp"
    return "googlenet"  # the reference's flagship trunk (def.prototxt:1)


def cmd_test(args) -> int:
    """The ``caffe test`` counterpart: restore a snapshot and run the
    TEST phase (same loss+metrics forward as training — the reference
    has no separate eval path, SURVEY.md §3.4) for ``test_iter`` batches."""
    built = _build_solver(args)
    if isinstance(built, int):
        return built
    solver, net_cfg, input_shape = built
    test_iter, _ = _build_data(
        net_cfg, "TEST", input_shape, seed=1, synthetic=args.synthetic,
        native=args.native,
    )
    if test_iter is None:
        log.error("net has no TEST MultibatchData layer")
        return 2
    iters = (solver.cfg.test_iter if args.iterations is None
             else args.iterations)
    if iters <= 0:
        log.error(
            "nothing to evaluate: %s",
            f"--iterations {iters} requests no batches" if args.iterations
            is not None else "solver test_iter is 0 and --iterations was "
            "not given",
        )
        return 2
    m = solver.evaluate(test_iter, iters)
    print(json.dumps({k: float(v) for k, v in sorted(m.items())}))
    return 0


def cmd_extract(args) -> int:
    """Embedding extraction — the metric-learning deployment product
    (the reference's pool5/L2Normalize feature is what retrieval systems
    consume; Caffe's `extract_features` workflow).  Runs the trunk in
    eval mode over the TEST (or TRAIN) source and writes embeddings +
    labels as .npy."""
    import numpy as np

    built = _build_solver(args)
    if isinstance(built, int):
        return built
    solver, net_cfg, input_shape = built
    phase = args.phase.upper()
    batches, _ = _build_data(
        net_cfg, phase, input_shape, seed=1, synthetic=args.synthetic,
        native=args.native,
    )
    if batches is None:
        log.error("net has no %s MultibatchData layer", phase)
        return 2

    import jax
    import jax.numpy as jnp

    def embed_fn(state, x):
        variables = {"params": state["params"]}
        if state["batch_stats"]:
            variables["batch_stats"] = state["batch_stats"]
        return solver.model.apply(variables, x, train=False)

    n_mesh = (len(solver.mesh.devices.flatten())
              if solver.mesh is not None else 1)
    embed_sharded = None
    if solver.mesh is not None:
        # Split the batch over the mesh like train/test steps do (their
        # sharding comes from in_shardings on the jitted step, not from
        # the device_put — a bare jit would run replicated).  Embedding
        # extraction is per-row, so this is pure data parallelism.
        from jax.sharding import NamedSharding, PartitionSpec as P

        embed_sharded = jax.jit(
            embed_fn,
            in_shardings=(None, NamedSharding(solver.mesh, P(solver.axis))),
        )
    embed_replicated = jax.jit(embed_fn)

    embs, labs = [], []
    for _ in range(args.batches):
        x, lab = next(batches)
        # Non-divisible batches (e.g. TEST batch 30 on a 4-mesh) fall
        # back to replicated execution rather than erroring.
        embed = (embed_sharded
                 if embed_sharded is not None and len(x) % n_mesh == 0
                 else embed_replicated)
        if solver.state is None:
            # Init from the actual batch shape (like Solver.step does):
            # the net's TRAIN and TEST layers may crop differently.
            solver.init(np.asarray(x)[:2])
        embs.append(np.asarray(embed(solver.state, jnp.asarray(x))))
        labs.append(np.asarray(lab))
    emb = np.concatenate(embs, axis=0)
    lab = np.concatenate(labs, axis=0)
    np.save(args.out + ".emb.npy", emb)
    np.save(args.out + ".labels.npy", lab)
    print(json.dumps({
        "embeddings": args.out + ".emb.npy",
        "labels": args.out + ".labels.npy",
        "shape": list(emb.shape),
        "mean_norm": float(np.linalg.norm(emb, axis=1).mean()),
    }))
    return 0


def _load_weights_into(solver, path: str):
    """Load a msgpack weights file into a solver, auto-converting to the
    model's MXU-variant layout when needed (s2d stem / fused 1x1s).

    Accepts the wrapped {"params", "batch_stats"} form written by
    import-caffemodel, or a bare params tree."""
    import flax.serialization

    with open(path, "rb") as f:
        tree = flax.serialization.msgpack_restore(f.read())
    batch_stats = None
    if isinstance(tree, dict) and set(tree) <= {"params", "batch_stats"}:
        params = tree["params"]
        batch_stats = tree.get("batch_stats") or None
    else:
        params = tree
    model = solver.model
    if getattr(model, "stem_s2d", False):
        from npairloss_tpu.models.layers import conv1_kernel_to_s2d
        import numpy as np

        k7 = np.asarray(params["conv1"]["Conv_0"]["kernel"])
        if k7.shape[0] == 7:  # plain-layout file -> s2d layout
            params["conv1"]["Conv_0"]["kernel"] = conv1_kernel_to_s2d(k7)
    if getattr(model, "fuse_1x1", False) and any(
        "b1x1" in v for v in params.values() if isinstance(v, dict)
    ):
        from npairloss_tpu.models import fuse_inception_1x1_params

        params, batch_stats = fuse_inception_1x1_params(params, batch_stats)
    solver.load_params(params, batch_stats)
    log.info("loaded pretrained params from %s", path)


def cmd_import_caffemodel(args) -> int:
    """Migrate a reference user's trained .caffemodel trunk: binary
    NetParameter blobs -> GoogLeNetEmbedding params -> msgpack file
    (consumed by ``train --weights``)."""
    import flax.serialization
    import jax
    import numpy as np

    from npairloss_tpu.config.caffemodel import parse_caffemodel
    from npairloss_tpu.models import get_model
    from npairloss_tpu.models.caffe_import import (
        caffe_layer_map,
        googlenet_params_from_caffemodel,
        resnet50_params_from_caffemodel,
    )

    with open(args.weights, "rb") as f:
        blobs = parse_caffemodel(f.read())
    log.info("caffemodel: %d layers with blobs", len(blobs))
    import jax.numpy as jnp

    model = get_model(args.model, dtype=jnp.float32)
    variables = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 224, 224, 3), jnp.float32),
            train=False,
        )
    )
    zeros = lambda tree: jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, np.float32), tree
    )
    if "resnet" in args.model.lower():
        params, batch_stats = resnet50_params_from_caffemodel(
            blobs, zeros(variables["params"]),
            zeros(variables["batch_stats"]),
        )
        mapped = len(jax.tree_util.tree_leaves(params))
    else:
        params = googlenet_params_from_caffemodel(
            blobs, zeros(variables["params"])
        )
        batch_stats = {}
        mapped = len(caffe_layer_map())
    with open(args.out, "wb") as f:
        f.write(flax.serialization.msgpack_serialize(
            {"params": params, "batch_stats": batch_stats}
        ))
    print(json.dumps({
        "out": args.out,
        "caffemodel_layers": len(blobs),
        "mapped_convs": mapped,
    }))
    return 0


def cmd_export_caffemodel(args) -> int:
    """The reverse migration: a trunk trained here -> .caffemodel bytes
    a Caffe deployment stack can consume."""
    import flax.serialization

    if not args.weights and not args.snapshot:
        log.error("pass --weights (msgpack) or --snapshot (.ckpt dir)")
        return 2
    if getattr(args, "solverstate_out", None):
        # Mirror load_caffe_solverstate's gate, and do it before even
        # restoring the tree: the variant trunks (googlenet_bn/s2d/
        # fused/mxu) have momentum trees the unnamed positional history
        # cannot map onto, and letting them past this point would raise
        # from googlenet_history_from_momentum only AFTER the
        # .caffemodel is written — defeating the validate-before-any-
        # write rule below.
        if args.model.lower() != "googlenet":
            log.error("--solverstate-out supports the plain 'googlenet' "
                      "trunk only (history blob order is pinned by the "
                      "plain-trunk layer map)")
            return 2

    from npairloss_tpu.config.caffemodel import write_caffemodel
    from npairloss_tpu.models.caffe_import import (
        caffemodel_layers_from_googlenet_params,
        caffemodel_layers_from_resnet50_params,
    )

    if args.snapshot:
        # Straight from a training snapshot: restore the raw Orbax tree
        # (params / batch_stats / opt) without needing a Solver.
        import orbax.checkpoint as ocp

        tree = ocp.StandardCheckpointer().restore(
            os.path.abspath(args.snapshot)
        )
    else:
        with open(args.weights, "rb") as f:
            tree = flax.serialization.msgpack_restore(f.read())
    batch_stats = {}
    if isinstance(tree, dict) and "params" in tree:
        params = tree["params"]
        batch_stats = tree.get("batch_stats") or {}
    else:
        params = tree
    # Validate --solverstate-out preconditions BEFORE any file is
    # written: failing halfway would leave a .caffemodel on disk next
    # to an error exit.
    opt = None
    if getattr(args, "solverstate_out", None):
        opt = tree.get("opt") if isinstance(tree, dict) else None
        if not opt:
            log.error("--solverstate-out needs a training snapshot "
                      "(--snapshot) carrying optimizer state; "
                      "--weights files hold parameters only")
            return 2

    if "resnet" in args.model.lower():
        layers = caffemodel_layers_from_resnet50_params(params, batch_stats)
    else:
        layers = caffemodel_layers_from_googlenet_params(params)
    blob = write_caffemodel(layers)
    with open(args.out, "wb") as f:
        f.write(blob)
    rec = {"out": args.out, "layers": len(layers), "bytes": len(blob)}
    if opt is not None:
        # Optimizer-state migration: momentum history + iteration as a
        # .solverstate next to the .caffemodel, so a Caffe stack can
        # `caffe train --snapshot` the run trained here.
        from npairloss_tpu.config.caffemodel import write_solverstate
        from npairloss_tpu.models.caffe_import import (
            googlenet_history_from_momentum,
        )

        if isinstance(opt, dict):
            momentum, step = opt["momentum_buf"], opt["step"]
        else:  # NamedTuple survived serialization
            momentum, step = opt.momentum_buf, opt.step
        ss = write_solverstate(
            int(step), googlenet_history_from_momentum(momentum),
            learned_net=os.path.basename(args.out),
        )
        with open(args.solverstate_out, "wb") as f:
            f.write(ss)
        rec["solverstate_out"] = args.solverstate_out
        rec["solverstate_iter"] = int(step)
    print(json.dumps(rec))
    return 0


def cmd_eval(args) -> int:
    """Full-gallery retrieval evaluation over extracted embeddings — the
    protocol papers report for the reference's datasets (every test
    image queries the whole test set), computed on-device in streamed
    query blocks.  Consumes the ``extract`` subcommand's .npy pair."""
    import numpy as np

    from npairloss_tpu.ops.eval_retrieval import evaluate_embeddings

    prefix = args.prefix
    emb_path = args.emb or prefix + ".emb.npy"
    lab_path = args.labels or prefix + ".labels.npy"
    for p in (emb_path, lab_path):
        if not os.path.exists(p):
            log.error("missing %s (run the extract subcommand first)", p)
            return 2
    emb = np.load(emb_path)
    lab = np.load(lab_path)
    if emb.shape[0] != lab.shape[0]:
        log.error(
            "embeddings/labels row mismatch: %s vs %s",
            emb.shape, lab.shape,
        )
        return 2
    m = evaluate_embeddings(
        emb, lab, ks=tuple(args.ks), query_block=args.query_block
    )
    rec = {
        "gallery_size": int(emb.shape[0]),
        "dim": int(emb.shape[1]),
        "classes": int(np.unique(lab).shape[0]),
        **{k: round(v, 4) for k, v in m.items()},
    }
    if args.nmi:
        from npairloss_tpu.ops.eval_retrieval import clustering_nmi

        rec["nmi"] = round(
            clustering_nmi(emb, lab, iters=args.kmeans_iters), 4
        )
    print(json.dumps(rec))
    return 0


def cmd_index(args) -> int:
    """Build (or inspect) a committed gallery index from the ``extract``
    subcommand's .npy pair — the offline half of the serving path
    (docs/SERVING.md).  ``--kind ivf`` clusters the gallery (shared
    k-means, ops/kmeans.py) and commits the IVF index; ``--add-to``
    appends to an existing index of EITHER kind (an IVF add re-assigns
    the new rows into the existing clusters); commits are atomic
    either way."""
    import numpy as np

    from npairloss_tpu.serve.index import index_info, load_index
    from npairloss_tpu.serve.ivf import IVFIndex

    if args.info:
        print(json.dumps(index_info(args.info)))
        return 0
    prefix = args.prefix
    emb_path = args.emb or prefix + ".emb.npy"
    lab_path = args.labels or prefix + ".labels.npy"
    for p in (emb_path, lab_path):
        if not os.path.exists(p):
            log.error("missing %s (run the extract subcommand first)", p)
            return 2
    emb = np.load(emb_path)
    lab = np.load(lab_path)
    if emb.shape[0] != lab.shape[0]:
        log.error("embeddings/labels row mismatch: %s vs %s",
                  emb.shape, lab.shape)
        return 2
    if args.add_to:
        idx = load_index(args.add_to)
        idx.add(emb, lab, normalize=not args.no_normalize)
    elif args.kind == "ivf":
        idx = IVFIndex.build_ivf(
            emb, lab, normalize=not args.no_normalize,
            clusters=args.clusters, iters=args.kmeans_iters,
            train_size=args.train_sample,
        )
        if args.parity_sample:
            # The recall birth certificate (docs/OBSERVABILITY.md
            # §Quality observatory): offline topk_recall parity per
            # scoring mode, stamped into the commit manifest so the
            # live shadow-recall gauge has a committed baseline.
            from npairloss_tpu.serve.ivf import measure_parity

            idx.parity = measure_parity(
                idx, probes=args.parity_probes,
                sample=args.parity_sample)
            log.info("ivf parity stamped: %s", idx.parity["recall"])
    else:
        from npairloss_tpu.serve.index import GalleryIndex

        idx = GalleryIndex.build(
            emb, lab, normalize=not args.no_normalize
        )
    out = idx.save(args.out or (args.add_to or prefix + ".gidx"))
    summary = {
        "out": out,
        "kind": idx.KIND,
        "rows": idx.size,
        "dim": idx.dim,
        "classes": int(np.unique(idx._host_labels).shape[0]),
    }
    if isinstance(idx, IVFIndex):
        summary["clusters"] = idx.n_clusters
        summary["cap"] = idx.layout.cap
        if idx.parity is not None:
            summary["parity"] = idx.parity
    print(json.dumps(summary))
    return 0


def cmd_serve(args) -> int:
    """The online path: load a committed gallery index (and optionally a
    training snapshot for raw-input queries), warm every padding bucket,
    and answer top-K queries over stdin/JSONL or localhost HTTP until
    EOF or a graceful SIGTERM drain (exit 75) — docs/SERVING.md."""
    import sys as _sys

    import jax

    from npairloss_tpu.resilience import (
        EXIT_PREEMPTED,
        PreemptionSignal,
        failpoints,
    )
    from npairloss_tpu.serve import (
        BatcherConfig,
        EngineConfig,
        GalleryIndex,
        IVFIndex,
        QueryEngine,
        RetrievalServer,
        ServerConfig,
    )
    from npairloss_tpu.serve.index import load_index, load_newest

    # Arg-only validations FIRST — a misconfigured invocation must fail
    # in milliseconds, not after the index loads and the buckets warm.
    if getattr(args, "remediate_dry_run", False):
        args.remediate = True  # a dry-run IS a remediation run
    if getattr(args, "watch_snapshots", None) and not args.snapshot:
        log.error("--watch-snapshots needs --snapshot/--model (the "
                  "hot-swap restores new params INTO the served model; "
                  "embedding-only serving can only watch --index-prefix)")
        return 2
    if getattr(args, "remediate", False) and \
            not getattr(args, "live_obs", False):
        log.error("--remediate needs --live-obs (remediation is driven "
                  "by the alert engine)")
        return 2
    if getattr(args, "remediation_config", None):
        # Parse NOW (it re-loads cheaply at wiring time): a typo'd
        # policy table must not cost an index load + warmup first.
        from npairloss_tpu.resilience.remediate import load_policies

        try:
            load_policies(args.remediation_config)
        except (OSError, ValueError) as e:
            log.error("--remediation-config %s: %s",
                      args.remediation_config, e)
            return 2
    tenant_registry = None
    if getattr(args, "tenant_config", None):
        # Parse + validate the tenants manifest NOW (jax-free): a typo'd
        # tenant table must fail before any index loads or bucket warms.
        from npairloss_tpu.serve.tenants import TenantRegistry

        try:
            tenant_registry = TenantRegistry.load(args.tenant_config)
        except (OSError, ValueError) as e:
            log.error("--tenant-config %s: %s", args.tenant_config, e)
            return 2
        if args.snapshot or getattr(args, "watch_snapshots", None):
            log.error("--tenant-config serves embedding queries only "
                      "(per-tenant model snapshots are not a thing yet) "
                      "— drop --snapshot/--watch-snapshots")
            return 2
        if getattr(args, "remediate", False):
            log.error("--tenant-config does not compose with "
                      "--remediate: per-tenant hot-swap is armed "
                      "automatically and per-tenant admission replaces "
                      "load_shed (docs/SERVING.md §Multi-tenant)")
            return 2
    if getattr(args, "wal_dir", None) and not args.index_prefix \
            and tenant_registry is None:
        log.error("--wal-dir needs --index-prefix (ingest checkpoints "
                  "publish under the prefix, and cold restart reloads "
                  "the newest one — docs/RESILIENCE.md §Durability); "
                  "in tenant mode each tenant's index_prefix plays "
                  "that role")
        return 2
    shadow_rate = float(getattr(args, "shadow_rate", 0.0) or 0.0)
    if not (0.0 <= shadow_rate <= 1.0):
        log.error("--shadow-rate must be in [0, 1], got %g", shadow_rate)
        return 2
    if shadow_rate > 0 and not getattr(args, "telemetry_dir", None):
        log.error("--shadow-rate needs --telemetry-dir (the recall "
                  "gauges ride the telemetry rows, and quality.jsonl "
                  "lands there — docs/OBSERVABILITY.md §Quality)")
        return 2
    if getattr(args, "qtrace", False) and \
            not getattr(args, "telemetry_dir", None):
        log.error("--qtrace needs --telemetry-dir (the exemplar "
                  "artifact qtrace.json lands there — "
                  "docs/OBSERVABILITY.md §Query tracing)")
        return 2

    if args.compile_cache:
        from npairloss_tpu.pipeline import enable_compile_cache

        enable_compile_cache(args.compile_cache)

    _pkg_handlers = [
        h for h in logging.getLogger("npairloss_tpu").handlers
        if not isinstance(h, logging.NullHandler)
    ]
    if not logging.getLogger().handlers and not _pkg_handlers:
        # Serving answers ride stdout; logs go to stderr so a JSONL
        # consumer never has to parse around them.
        logging.basicConfig(level=logging.INFO, format="%(message)s",
                            stream=_sys.stderr)

    mesh = None
    n_dev = len(jax.devices())
    want = args.mesh if args.mesh is not None else (n_dev if n_dev > 1 else 1)
    if want > 1:
        from npairloss_tpu.parallel import data_parallel_mesh

        mesh = data_parallel_mesh(jax.devices()[:want])

    index = index_path = None
    if args.index_prefix:
        found = load_newest(args.index_prefix, mesh=mesh)
        if found is None:
            log.error("no valid index under prefix %r", args.index_prefix)
            return 2
        index_path, index = found
        log.info("serving index %s", index_path)
    elif args.index:
        index_path = os.path.abspath(args.index)
        index = load_index(args.index, mesh=mesh)
    # Reconcile the committed structure with the requested serving
    # structure (docs/SERVING.md §Approximate index): a flat commit can
    # serve through the IVF probe path (clustered in-memory at startup)
    # and an IVF commit can serve flat (the exact-scan recall oracle) —
    # the committed artifact never dictates the serving posture.  ONE
    # closure, because the hot-swap remediation must apply the same
    # reconciliation to every swapped-in index (a flat commit must not
    # demote an IVF tier at the first swap).
    def _reconcile_index(idx):
        if args.index_kind == "ivf" and not isinstance(idx, IVFIndex):
            log.info("clustering flat index into IVF (%s clusters)...",
                     args.ivf_clusters or "auto")
            return IVFIndex.from_gallery(idx, clusters=args.ivf_clusters)
        if args.index_kind == "flat" and isinstance(idx, IVFIndex):
            log.info("serving ivf commit through the flat exact scan")
            return GalleryIndex.build(
                idx._host_emb, idx._host_labels, ids=idx.ids,
                mesh=mesh, normalize=False)
        return idx

    if index is not None:
        index = _reconcile_index(index)

    # Tenant mode loads one index PER TENANT, each reconciled to its
    # own declared kind (a mixed flat/IVF tier behind one front end).
    tenant_indexes = {}
    if tenant_registry is not None:
        from npairloss_tpu.serve.tenants import reconcile_index_kind

        for spec_t in tenant_registry:
            found = load_newest(spec_t.index_prefix, mesh=mesh)
            if found is None:
                log.error("tenant %r: no valid index under prefix %r",
                          spec_t.tenant_id, spec_t.index_prefix)
                return 2
            tpath, tidx = found
            tidx = reconcile_index_kind(
                tidx, spec_t.index_kind,
                clusters=args.ivf_clusters, mesh=mesh)
            tenant_indexes[spec_t.tenant_id] = (tpath, tidx)
            log.info("tenant %r: serving index %s (%s)",
                     spec_t.tenant_id, tpath, spec_t.index_kind)

    # Durable-ingest arm (docs/RESILIENCE.md §Durability): open the WAL
    # (recovery truncates any torn tail loudly), then replay every
    # record ABOVE the loaded artifact's watermark into the pending
    # buffer — exactly-once: records the snapshot already contains are
    # skipped.  Pending records reach a SERVED index only through
    # checkpoint publication + hot-swap; an in-place add to the live
    # gallery would recompile on the serving path.
    wal = None
    if getattr(args, "wal_dir", None) and tenant_registry is None:
        import numpy as np

        from npairloss_tpu.resilience.wal import (
            WalCorruptionError,
            WriteAheadLog,
        )
        from npairloss_tpu.serve.index import INDEX_SUFFIX
        from npairloss_tpu.serve.server import decode_ingest_payload

        base_watermark = int(getattr(index, "ingest_watermark", 0))
        _ingest = {"base": index_path, "pending": []}

        def _apply_ingest(payload):
            _ingest["pending"].append(
                (int(payload["seq"]), decode_ingest_payload(payload)))

        def _publish_checkpoint(wm: int):
            pending = [p for p in _ingest["pending"] if p[0] <= wm]
            if not pending:
                return None
            base = load_index(_ingest["base"], mesh=mesh)
            emb = np.concatenate([d[0] for _, d in pending])
            labels = np.concatenate([d[1] for _, d in pending])
            ids = np.concatenate([d[2] for _, d in pending])
            base.add(emb, labels, ids=ids)
            base.ingest_watermark = wm
            # 'w' sorts after every digit, so checkpoints always win
            # load_newest over the plain numbered commits they grew
            # from, and among themselves by watermark.
            path = base.save(
                f"{args.index_prefix}w{wm:012d}{INDEX_SUFFIX}")
            _ingest["base"] = path
            _ingest["pending"] = [p for p in _ingest["pending"]
                                  if p[0] > wm]
            log.info("ingest checkpoint: %s (watermark %d, +%d row(s))",
                     path, wm, int(emb.shape[0]))
            return path

        try:
            wal = WriteAheadLog(
                args.wal_dir,
                flush_interval_s=max(args.wal_flush_ms, 0.0) / 1e3)
            replayed = 0
            for payload in wal.replay(after_seq=base_watermark):
                _apply_ingest(payload)
                replayed += 1
        except WalCorruptionError as e:
            log.error("--wal-dir %s refused: %s", args.wal_dir, e)
            return 2
        _wal_st = wal.stats()
        log.info("wal: recovered %s — last_seq %d, replayed %d "
                 "record(s) above watermark %d, torn_records %d",
                 args.wal_dir, _wal_st["last_seq"], replayed,
                 base_watermark, _wal_st["torn_records"])

    # Per-tenant durable ingest: the same WAL discipline, one log per
    # tenant under --wal-dir/<tenant_id>, each checkpointing under its
    # own index_prefix — one tenant's ingest volume never advances (or
    # corrupts) a neighbor's watermark.
    tenant_wals = []
    tenant_ingests = {}
    if getattr(args, "wal_dir", None) and tenant_registry is not None:
        import numpy as np

        from npairloss_tpu.resilience.wal import (
            WalCorruptionError,
            WriteAheadLog,
        )
        from npairloss_tpu.serve.index import INDEX_SUFFIX
        from npairloss_tpu.serve.server import decode_ingest_payload
        from npairloss_tpu.serve.tenants import TenantIngest

        for spec_t in tenant_registry:
            tid = spec_t.tenant_id
            tpath, tidx = tenant_indexes[tid]
            t_watermark = int(getattr(tidx, "ingest_watermark", 0))
            t_state = {"base": tpath, "pending": []}

            def _t_apply(payload, _st=t_state):
                _st["pending"].append(
                    (int(payload["seq"]), decode_ingest_payload(payload)))

            def _t_publish(wm, _st=t_state, _spec=spec_t):
                pending = [p for p in _st["pending"] if p[0] <= wm]
                if not pending:
                    return None
                base = load_index(_st["base"], mesh=mesh)
                emb = np.concatenate([d[0] for _, d in pending])
                labels = np.concatenate([d[1] for _, d in pending])
                ids = np.concatenate([d[2] for _, d in pending])
                base.add(emb, labels, ids=ids)
                base.ingest_watermark = wm
                path = base.save(
                    f"{_spec.index_prefix}w{wm:012d}{INDEX_SUFFIX}")
                _st["base"] = path
                _st["pending"] = [p for p in _st["pending"]
                                  if p[0] > wm]
                log.info("tenant %r ingest checkpoint: %s (watermark "
                         "%d, +%d row(s))", _spec.tenant_id, path, wm,
                         int(emb.shape[0]))
                return path

            t_wal_dir = os.path.join(args.wal_dir, tid)
            try:
                t_wal = WriteAheadLog(
                    t_wal_dir,
                    flush_interval_s=max(args.wal_flush_ms, 0.0) / 1e3)
                replayed = 0
                for payload in t_wal.replay(after_seq=t_watermark):
                    _t_apply(payload)
                    replayed += 1
            except WalCorruptionError as e:
                log.error("--wal-dir %s (tenant %r) refused: %s",
                          t_wal_dir, tid, e)
                for w in tenant_wals:
                    w.close()
                return 2
            tenant_wals.append(t_wal)
            tenant_ingests[tid] = TenantIngest(
                t_wal, _t_apply, checkpoint_fn=_t_publish,
                checkpoint_every=args.wal_checkpoint_every,
                watermark=max(t_watermark, t_wal.last_seq),
                checkpoint_watermark=t_watermark)
            log.info("tenant %r durable ingest armed: wal %s, replayed "
                     "%d record(s) above watermark %d", tid, t_wal_dir,
                     replayed, t_watermark)

    model = state = None
    input_shape = None
    if args.snapshot:
        from npairloss_tpu.models import get_model
        from npairloss_tpu.train import restore_for_inference

        model = get_model(args.model or "googlenet")
        state = restore_for_inference(args.snapshot)
        side = args.input_size
        input_shape = (side, side, 3)

    buckets = tuple(int(b) for b in args.buckets.split(","))
    telemetry = None
    live = None
    tel_dir = getattr(args, "telemetry_dir", None)
    trace_dir = getattr(args, "trace_dir", None)
    if getattr(args, "live_obs", False):
        # Live observatory (docs/OBSERVABILITY.md §Live): the registry
        # is FED by the telemetry rows, so live obs without a metrics
        # stream would silently watch nothing — refuse loudly.
        if not tel_dir:
            log.error("--live-obs needs --telemetry-dir (the registry "
                      "is fed by the run's metric rows)")
            return 2
        from npairloss_tpu.obs.live import (
            LiveObservatory,
            default_watchdogs,
            load_slo_config,
        )

        if getattr(args, "slo_config", None):
            specs = load_slo_config(args.slo_config)
        else:
            # The queue-depth gauge reports the TIER-WIDE sum across
            # replica batchers, so the saturation bound must scale the
            # same way — or an N-replica tier pages (and sheds) at 1/N
            # of its real capacity.
            specs = default_watchdogs(
                "serve", max_queue=args.max_queue * args.replicas)
        if tenant_registry is not None:
            # Per-tenant SLOs over the labeled metric streams
            # (serve_p99_ms{tenant=...}) — one evaluator, one alert
            # engine, tenant-scoped tenant_*@<id> alert names.
            from npairloss_tpu.serve.tenants import tenant_slo_specs

            specs = list(specs)
            for spec_t in tenant_registry:
                specs.extend(tenant_slo_specs(spec_t))
        live = LiveObservatory(specs, out_dir=tel_dir)
    if tel_dir or trace_dir:
        from npairloss_tpu.obs import RunTelemetry

        telemetry = RunTelemetry(
            tel_dir or trace_dir, metrics=bool(tel_dir),
            extra_sinks=(live.sink,) if live is not None else (),
        )
        if tel_dir:
            telemetry.write_manifest(config={
                "serve": True,
                "index": args.index or args.index_prefix,
                "index_kind": args.index_kind,
                "probes": args.probes,
                "scoring": args.scoring,
                "probe_impl": args.probe_impl,
                "replicas": args.replicas,
                "admission": args.admission,
                "top_k": args.top_k,
                "buckets": list(buckets),
                "deadline_ms": args.deadline_ms,
                "max_queue": args.max_queue,
                "live_obs": live is not None,
                "slo_config": getattr(args, "slo_config", None),
                "remediate": bool(getattr(args, "remediate", False)
                                  or getattr(args, "remediate_dry_run",
                                             False)),
                "shadow_rate": shadow_rate,
                "qtrace": bool(getattr(args, "qtrace", False)),
                **({"tenants": tenant_registry.ids()}
                   if tenant_registry is not None else {}),
            })

    if args.admission != "off" and live is None:
        log.error("--admission %s needs --live-obs (admission is driven "
                  "by the SLO burn-rate engine)", args.admission)
        return 2
    if args.replicas < 1:
        log.error("--replicas must be >= 1, got %d", args.replicas)
        return 2

    preempt = PreemptionSignal().install()
    shadow = None
    tenant_shadows = []
    tenant_swapper = None
    try:
        from npairloss_tpu.serve import Freshness

        tenant_entries = {}
        programs = None
        if tenant_registry is None:
            engine_cfg = EngineConfig(
                top_k=args.top_k, buckets=buckets,
                gallery_block=args.gallery_block,
                probes=args.probes, scoring=args.scoring,
                probe_impl=args.probe_impl,
            )
            engine = QueryEngine(
                index, engine_cfg,
                model=model, state=state, telemetry=telemetry,
            )
            # Replicas share the primary's compiled programs: one
            # warmup warms the whole tier, and with --compile-cache a
            # restarted replica deserializes instead of recompiling.
            engines = [engine] + [
                QueryEngine(index, engine_cfg, model=model, state=state,
                            telemetry=telemetry,
                            share_compiled_with=engine)
                for _ in range(args.replicas - 1)
            ]
            if not args.no_warmup:
                engine.warmup(input_shape)
                for e in engines[1:]:
                    e.warmed = True
            freshness = Freshness.collect(
                index=index, index_path=index_path,
                snapshot_path=args.snapshot or None,
            )
        else:
            # Tenant mode: one engine set PER TENANT through the shared
            # ProgramCache — bucketed shapes make the jitted programs
            # tenant-agnostic, so tenants at the same geometry share
            # one program family and tenant count never multiplies
            # compiles (the test_tenants.py assertion).
            from npairloss_tpu.serve.tenants import (
                ProgramCache,
                QuotaGate,
                TenantEntry,
                TenantTelemetry,
                tenant_slo_specs,
            )

            programs = ProgramCache()
            for spec_t in tenant_registry:
                tid = spec_t.tenant_id
                tpath, tidx = tenant_indexes[tid]
                t_cfg = EngineConfig(
                    top_k=args.top_k, buckets=buckets,
                    gallery_block=args.gallery_block,
                    probes=args.probes, scoring=args.scoring,
                    probe_impl=spec_t.probe_impl or args.probe_impl,
                )
                t_tel = (TenantTelemetry(telemetry, tid)
                         if telemetry is not None else None)
                primary = programs.engine_for(tidx, t_cfg,
                                              telemetry=t_tel)
                if not args.no_warmup:
                    primary.warmup(None)
                t_engines = [primary] + [
                    QueryEngine(tidx, t_cfg, telemetry=t_tel,
                                share_compiled_with=primary)
                    for _ in range(args.replicas - 1)
                ]
                for e in t_engines[1:]:
                    e.warmed = primary.warmed
                quota = None
                if spec_t.quota_qps > 0:
                    quota = QuotaGate(
                        spec_t.quota_qps,
                        burst_s=spec_t.quota_burst_s,
                        registry=(live.registry.view(tenant=tid)
                                  if live is not None else None))
                t_adm = None
                t_slos = tenant_slo_specs(spec_t)
                if spec_t.admission and live is not None and t_slos:
                    from npairloss_tpu.serve.admission import (
                        AdmissionConfig,
                        AdmissionController,
                    )

                    t_adm = AdmissionController(
                        AdmissionConfig(
                            slo_names=tuple(s.name for s in t_slos),
                            probe_every=spec_t.probe_every),
                        registry=live.registry.view(tenant=tid))
                    live.add_listener(t_adm.on_statuses)
                tenant_entries[tid] = TenantEntry(
                    spec_t, t_engines,
                    freshness=Freshness.collect(index=tidx,
                                                index_path=tpath),
                    quota=quota, admission=t_adm,
                    ingest=tenant_ingests.get(tid))
            first_entry = next(iter(tenant_entries.values()))
            engines = first_entry.engines
            # The server-level freshness stays None: in tenant mode
            # every freshness fact is per-entry (the healthz contract).
            freshness = None
        admission = None
        if args.admission == "slo":
            from npairloss_tpu.serve.admission import controller_from_args

            admission = controller_from_args(
                args.admission_slos, registry=live.registry)
            live.add_listener(admission.on_statuses)
        qtracer = None
        if getattr(args, "qtrace", False):
            from npairloss_tpu.obs.qtrace import QTraceConfig, QueryTracer

            slo_ms = float(getattr(args, "qtrace_slo_ms", 0.0) or 0.0)
            if slo_ms <= 0 and live is not None:
                # Default the per-query SLO to the armed p99 watchdog's
                # target: one latency bar, two enforcement points (the
                # pager on the aggregate, the exemplar on the query).
                for spec in specs:
                    if spec.metric == "serve_p99_ms" and spec.op == "<=":
                        slo_ms = float(spec.target)
                        break
            if slo_ms <= 0:
                slo_ms = 250.0
            qtracer = QueryTracer(
                QTraceConfig(
                    exemplars=args.qtrace_exemplars, slo_ms=slo_ms),
                registry=live.registry if live is not None else None,
                out_path=os.path.join(tel_dir, "qtrace.json"),
            )
            log.info("query tracing armed: slo %.1f ms, %d exemplars",
                     slo_ms, args.qtrace_exemplars)
        server = RetrievalServer(
            engines,
            BatcherConfig(max_batch=buckets[-1],
                          max_delay_ms=args.deadline_ms,
                          max_queue=args.max_queue),
            ServerConfig(metrics_window=args.metrics_window,
                         explicit_drops=getattr(args, "explicit_drops",
                                                False),
                         poll_s=args.poll_s),
            telemetry=telemetry, preempt=preempt,
            freshness=freshness, live=live, admission=admission,
            input_shape=input_shape, qtrace=qtracer,
        )
        if tenant_registry is not None:
            from npairloss_tpu.serve.tenants import TenantSwapper

            server.enable_tenants(tenant_entries)
            # Per-tenant hot-swap watch, always on in tenant mode: the
            # "nothing newer" sweep costs a listdir per tenant, and a
            # published checkpoint/commit under any tenant's prefix
            # swaps THAT tenant in place while its neighbors keep
            # answering.
            tenant_swapper = TenantSwapper(
                server, programs=programs, mesh=mesh,
                telemetry=telemetry, ivf_clusters=args.ivf_clusters)
            tenant_swapper.start(period_s=2.0)
            log.info("multi-tenant serving: %d tenant(s) %s; hot-swap "
                     "sweep every 2.0s", len(tenant_entries),
                     sorted(tenant_entries))
        if wal is not None:
            server.attach_wal(
                wal, _apply_ingest,
                checkpoint_fn=_publish_checkpoint,
                checkpoint_every=args.wal_checkpoint_every,
                watermark=max(base_watermark, wal.last_seq),
                checkpoint_watermark=base_watermark)
            log.info("durable ingest armed: wal %s, flush %.1f ms, "
                     "checkpoint every %d batch(es)", args.wal_dir,
                     args.wal_flush_ms, args.wal_checkpoint_every)
        if shadow_rate > 0 and tenant_registry is not None:
            # Per-tenant quality observatories: each tenant gets its
            # own deterministic sampler, oracle, floor and
            # quality.<tenant>.jsonl — a recall regression in one
            # gallery can never hide inside a healthy aggregate.  The
            # TenantTelemetry facade stamps the tenant into every
            # quality row, so the recall gauges land labeled
            # (serve_recall_at_K{tenant=...}) where the tenant's
            # recall SLO reads them.
            from npairloss_tpu.obs.quality.shadow import (
                ShadowConfig,
                ShadowScorer,
            )
            from npairloss_tpu.serve.tenants import TenantTelemetry

            shadow_ks = tuple(k for k in (1, 5, 10) if k <= args.top_k)
            for t_i, tid in enumerate(tenant_entries):
                entry = tenant_entries[tid]
                spec_t = entry.spec
                baseline = None
                try:
                    from npairloss_tpu.resilience.snapshot import (
                        read_manifest,
                    )

                    raw = read_manifest(
                        tenant_indexes[tid][0]).get("parity")
                    baseline = raw if isinstance(raw, dict) else None
                except Exception:  # noqa: BLE001 — baseline is optional evidence
                    baseline = None
                floor = floor_metric = None
                if spec_t.recall_floor is not None:
                    if spec_t.recall_k in shadow_ks:
                        floor = spec_t.recall_floor
                        floor_metric = (
                            f"serve_recall_at_{spec_t.recall_k}")
                    else:
                        log.warning(
                            "tenant %r recall floor targets recall@%d "
                            "but --top-k %d samples only recall@{%s} — "
                            "that floor can never see a sample", tid,
                            spec_t.recall_k, args.top_k,
                            ",".join(str(k) for k in shadow_ks))
                entry.shadow = ShadowScorer(
                    (lambda e=entry: e.engines[0].index),
                    ShadowConfig(rate=shadow_rate, ks=shadow_ks,
                                 window=args.shadow_window,
                                 seed=args.shadow_seed + t_i),
                    telemetry=TenantTelemetry(telemetry, tid),
                    out_path=os.path.join(tel_dir,
                                          f"quality.{tid}.jsonl"),
                    baseline=baseline,
                    recall_floor=floor, floor_metric=floor_metric,
                ).start()
                tenant_shadows.append(entry.shadow)
            log.info("per-tenant shadow scoring armed: rate %g, "
                     "window %d, %d scorer(s)", shadow_rate,
                     args.shadow_window, len(tenant_shadows))
        elif shadow_rate > 0:
            # Quality observatory (docs/OBSERVABILITY.md §Quality):
            # shadow-score a deterministic sample of live queries
            # against the flat oracle, off the hot path.  The floor the
            # quality log declares is whatever recall SLO this run
            # armed; the baseline is the served IVF commit's parity
            # birth certificate (absent for flat/in-memory indexes).
            from npairloss_tpu.obs.quality.shadow import (
                ShadowConfig,
                ShadowScorer,
            )

            baseline = None
            try:
                from npairloss_tpu.resilience.snapshot import (
                    read_manifest,
                )

                raw = read_manifest(index_path).get("parity")
                baseline = raw if isinstance(raw, dict) else None
            except Exception:  # noqa: BLE001 — baseline is optional evidence
                baseline = None
            shadow_ks = tuple(k for k in (1, 5, 10) if k <= args.top_k)
            floor = floor_metric = None
            if live is not None:
                for spec in specs:
                    if not (spec.metric.startswith("serve_recall_at_")
                            and spec.op == ">="):
                        continue
                    tail = spec.metric.rsplit("_", 1)[-1]
                    if tail.isdigit() and int(tail) in shadow_ks:
                        floor, floor_metric = spec.target, spec.metric
                        break
                    # A floor on a K the shadow can never sample
                    # (--top-k below it) would be silently inert —
                    # SLO, breach detection, and the gate would all
                    # sleep through a real regression.  Say so loudly.
                    log.warning(
                        "recall SLO %s targets %s but --top-k %d "
                        "samples only recall@{%s} — that floor can "
                        "never see a sample (raise --top-k or lower "
                        "the SLO's K)", spec.name, spec.metric,
                        args.top_k,
                        ",".join(str(k) for k in shadow_ks))
            shadow = ShadowScorer(
                lambda: server.engine.index,
                ShadowConfig(rate=shadow_rate,
                             ks=shadow_ks,
                             window=args.shadow_window,
                             seed=args.shadow_seed),
                telemetry=telemetry,
                out_path=os.path.join(tel_dir, "quality.jsonl"),
                baseline=baseline,
                recall_floor=floor, floor_metric=floor_metric,
            ).start()
            server.shadow = shadow
            log.info("shadow scoring armed: rate %g, window %d%s",
                     shadow_rate, args.shadow_window,
                     f", floor {floor} on {floor_metric}"
                     if floor is not None else "")
        if getattr(args, "remediate", False):
            # Alert→actuation (docs/RESILIENCE.md §Remediation): bind
            # the live alerts to the serve-side actions this run can
            # actually perform, audited to remediation.jsonl.
            # (--live-obs presence was validated before the preempt
            # handler went in.)
            from npairloss_tpu.resilience.remediate import (
                RemediationEngine,
                default_policies,
                load_policies,
            )

            explicit = bool(getattr(args, "remediation_config", None))
            policies = (load_policies(args.remediation_config)
                        if explicit else default_policies("serve"))
            actions = {}
            if args.index_prefix or getattr(args, "watch_snapshots",
                                            None):
                from npairloss_tpu.serve.hotswap import SnapshotSwapper

                swapper = SnapshotSwapper(
                    server, mesh=mesh,
                    index_prefix=args.index_prefix,
                    snapshot_prefix=getattr(args, "watch_snapshots",
                                            None),
                    model=model, input_shape=input_shape,
                    telemetry=telemetry,
                    index_transform=_reconcile_index,
                )
                actions["snapshot_hotswap"] = swapper.swap
            actions["rewarm"] = lambda alert: server.rewarm()
            if isinstance(index, IVFIndex):
                # Recall-burn actuation (docs/OBSERVABILITY.md
                # §Quality): widen the probe set, flat-fallback past
                # it.  Only an IVF tier has the knob — the default
                # policy table filters itself out elsewhere.
                from npairloss_tpu.obs.quality.escalate import (
                    ProbeEscalator,
                )

                escalator = ProbeEscalator(server, telemetry=telemetry)
                actions["escalate_probes"] = escalator.escalate
            if admission is None and any(p.action == "load_shed"
                                         for p in policies):
                # Remediation-driven shedding needs the throttle in the
                # submit path: a forced-only controller (NO burn
                # listener — it sheds only while the load_shed policy
                # holds it engaged).
                from npairloss_tpu.serve.admission import (
                    AdmissionConfig,
                    AdmissionController,
                )

                admission = AdmissionController(
                    AdmissionConfig(), registry=live.registry)
                server.admission = admission
            if admission is not None:
                actions["load_shed"] = (admission.engage,
                                        admission.release)
            if not explicit:
                # The default table ships every policy; keep the ones
                # this invocation registered an actuator for.  An
                # EXPLICIT config is never filtered — a policy without
                # its action is a loud config error.
                policies = [p for p in policies if p.action in actions]
            try:
                remediation = RemediationEngine(
                    policies, actions,
                    log_path=os.path.join(tel_dir, "remediation.jsonl"),
                    dry_run=getattr(args, "remediate_dry_run", False),
                )
            except ValueError as e:
                # An explicit config naming an action this invocation
                # has no actuator for (snapshot_hotswap without a
                # watched prefix) — a config error, not a crash.
                log.error("--remediation-config %s: %s",
                          args.remediation_config, e)
                return 2
            server.remediation = remediation
            live.set_remediation(remediation)
            log.info("remediation armed: %s%s",
                     ", ".join(f"{p.name}({p.slo}->{p.action})"
                               for p in policies) or "no policies",
                     " [DRY-RUN]" if remediation.dry_run else "")
        if live is not None:
            # Freshness probe: ages are server state, not metric rows —
            # each evaluator tick republishes them so the staleness
            # watchdogs see a continuous stream.  Reads the SERVER's
            # freshness (not a construction-time snapshot): a hot-swap
            # republishes identity + ages, and the probe must see the
            # drop.  The serve.stale_model failpoint poisons the
            # published model age so the staleness→hot-swap loop is
            # deterministically drivable.
            import time as _time

            _qtrace_last = [0.0]

            def _freshness_probe():
                if qtracer is not None:
                    # Crash-consistent exemplar artifact: checkpoint
                    # qtrace.json on the probe cadence (atomic
                    # tmp+rename), so a host crash loses at most a
                    # couple of seconds of markers instead of the whole
                    # artifact — the drain write stays the final word.
                    now = _time.monotonic()
                    if now - _qtrace_last[0] >= 2.0:
                        _qtrace_last[0] = now
                        try:
                            qtracer.write()
                        except OSError as e:
                            log.error("qtrace checkpoint failed: %s", e)
                if wal is not None:
                    # Ingest-durability gauges (/metrics + the SLO
                    # registry): what the tier has acked vs published,
                    # and the torn-tail evidence recovery counted.
                    st = wal.stats()
                    live.registry.set("serve_ingest_watermark",
                                      float(server.ingest_watermark))
                    live.registry.set("serve_wal_durable_seq",
                                      float(st["durable_seq"]))
                    live.registry.set("serve_wal_torn_records",
                                      float(st["torn_records"]))
                if server.tenants:
                    # Per-tenant freshness/ingest gauges, labeled —
                    # each tenant's staleness and durability watermark
                    # is its own metric stream.
                    for tid in sorted(server.tenants):
                        entry = server.tenants[tid]
                        view = live.registry.view(tenant=tid)
                        if entry.ingest is not None:
                            ist = entry.ingest.stats()
                            view.set("serve_ingest_watermark",
                                     float(ist["watermark"]))
                            wst = ist.get("wal") or {}
                            if "durable_seq" in wst:
                                view.set("serve_wal_durable_seq",
                                         float(wst["durable_seq"]))
                        f_t = entry.freshness
                        if f_t is None:
                            continue
                        for key, v in f_t.ages().items():
                            view.set(f"serve_{key}", v)
                f = server.freshness
                if f is None:
                    return
                ages = f.ages()
                if failpoints.should_fire("serve.stale_model"):
                    ages["model_age_s"] = (
                        ages.get("model_age_s", 0.0)
                        + failpoints.STALE_AGE_FAULT_S)
                for key, v in ages.items():
                    live.registry.set(f"serve_{key}", v)

            live.add_probe(_freshness_probe)
            # Started AFTER warmup: the first windows must reflect
            # serving, not seconds-long XLA compiles.
            live.start(period_s=args.slo_tick)
        if args.http is not None:
            return server.run_http(args.http)
        return server.run_jsonl(_sys.stdin, _sys.stdout)
    finally:
        preempt.uninstall()
        if tenant_swapper is not None:
            try:
                tenant_swapper.stop()
            except Exception as e:  # noqa: BLE001
                log.error("tenant swapper stop failed: %s", e)
        if wal is not None:
            try:
                # Drain-time checkpoint already ran inside the server's
                # drain; this is the final fsync + flusher join.
                wal.close()
            except Exception as e:  # noqa: BLE001
                log.error("wal close failed: %s", e)
        for t_wal in tenant_wals:
            try:
                t_wal.close()
            except Exception as e:  # noqa: BLE001
                log.error("tenant wal close failed: %s", e)
        for t_sh in tenant_shadows:
            try:
                t_sh.close()
            except Exception as e:  # noqa: BLE001
                log.error("tenant shadow scorer close failed: %s", e)
        if shadow is not None:
            try:
                # Drain the shadow queue (every accepted sample
                # scored), flush the final window + summary record —
                # BEFORE the live stop, so the last recall rows reach
                # the final tick, and before telemetry closes.
                shadow.close()
            except Exception as e:  # noqa: BLE001
                log.error("shadow scorer close failed: %s", e)
        if live is not None:
            try:
                # Final tick inside: an alert state that changed right
                # before the drain still reaches alerts.jsonl.
                live.stop()
            except Exception as e:  # noqa: BLE001
                log.error("live-obs stop failed: %s", e)
        if telemetry is not None:
            try:
                telemetry.close()
            except Exception as e:  # noqa: BLE001
                log.error("telemetry close failed: %s", e)


def cmd_timeline(args) -> int:
    """``timeline RUNDIR`` — merge every timeline source under a run
    directory (trainer rank traces, the serve host trace, qtrace
    exemplar span trees, alert/remediation/chaos instants) into one
    Perfetto-loadable ``timeline.json`` (docs/OBSERVABILITY.md §Query
    tracing).  Stdlib-only: runs on any box that can read the
    artifacts."""
    from npairloss_tpu.obs.fleet.merge_traces import merge_timeline
    from npairloss_tpu.obs.tracing import validate_chrome_trace

    run_dir = os.path.abspath(args.run_dir)
    if not os.path.isdir(run_dir):
        log.error("timeline: %s is not a directory", run_dir)
        return 2
    path, merged = merge_timeline(run_dir, out_path=args.out)
    if path is None:
        log.error(
            "timeline: no mergeable source under %s (looked for rank "
            "traces, serve_tel/trace.json, qtrace.json, alerts.jsonl, "
            "remediation.jsonl, gameday.json)", run_dir)
        return 1
    err = validate_chrome_trace(merged)
    if err is not None:
        log.error("merged timeline failed trace validation: %s", err)
        return 1
    sources = merged["otherData"]["sources"]
    log.info("timeline: %d event(s) from %s", len(merged["traceEvents"]),
             ", ".join(k for k, v in sources.items() if v))
    print(json.dumps({"timeline": path,
                      "events": len(merged["traceEvents"]),
                      "sources": sources}))
    return 0


def cmd_watch(args) -> int:
    """``watch RUNDIR`` — the live observatory's OFFLINE feed
    (docs/OBSERVABILITY.md §Live): tail a run directory's telemetry
    streams (legacy metrics.jsonl and the fleet per-rank
    telemetry.r<k>.jsonl alike) through the SAME SLO engine the
    in-process path runs, each record evaluated at its own wall_time —
    one evaluator, two feeds.  Backend-free: no jax object is ever
    built, so it runs on any box that can read the artifacts."""
    from npairloss_tpu.obs.live import (
        default_watchdogs,
        load_slo_config,
        watch_run_dir,
    )

    if args.slo_config:
        specs = load_slo_config(args.slo_config)
    else:
        specs = []
        seen = set()
        for kind in args.watchdogs.split(","):
            kind = kind.strip()
            if not kind:
                continue
            for spec in default_watchdogs(kind):
                if spec.name not in seen:
                    seen.add(spec.name)
                    specs.append(spec)
        if not specs:
            log.error("--watchdogs %r names no presets", args.watchdogs)
            return 2

    def emit(event) -> None:
        print(json.dumps(event), flush=True)

    try:
        summary = watch_run_dir(
            args.run_dir, specs,
            follow=args.follow, poll_s=args.poll_s,
            out_path=args.out, emit=emit,
            stop_after_s=getattr(args, "for_s", None),
        )
    except FileNotFoundError as e:
        log.error("%s", e)
        return 2
    except KeyboardInterrupt:
        print("", file=sys.stderr)
        return 0
    print(json.dumps(summary, default=str))
    # Exit code mirrors the bench_check --alerts gate: an SLO still
    # burning when the watch ends is an actionable state for scripts.
    return 1 if any(a["severity"] == "critical"
                    for a in summary["active"].values()) else 0


def _add_staticcheck_options(sc) -> None:
    """The staticcheck option vocabulary, restated here so argparse
    construction stays import-free (the bench-parent contract, like
    _PRECISION_CHOICES).  Option strings, choices, and defaults are
    pinned equal to analysis.runner's own parser by
    tests/test_staticcheck.py — both front doors feed one
    ``run_from_args``, so drift is a test failure."""
    sc.add_argument("root", nargs="?", default=None,
                    help="tree to scan (default: this repo)")
    sc.add_argument("--pass", dest="passes", action="append",
                    choices=list(_STATICCHECK_PASSES), metavar="NAME",
                    help="run only the named pass(es); repeatable "
                    f"(default: all of {list(_STATICCHECK_PASSES)})")
    sc.add_argument("--diff", metavar="BASE",
                    help="restrict findings to files changed since the "
                    "git ref (the fast incremental ci.sh hook)")
    sc.add_argument("--allowlist", metavar="PATH",
                    help="allowlist JSON (default: "
                    "<root>/scripts/staticcheck_allow.json)")
    sc.add_argument("--out", metavar="PATH",
                    default="staticcheck_report.json",
                    help="where the npairloss-staticcheck-v1 report "
                    "lands (default %(default)s; '-' disables)")
    sc.add_argument("--update-timings", dest="update_timings",
                    metavar="PYTEST_LOG",
                    help="regenerate tests/timing_history.json from a "
                    "pytest --durations=0 log, then exit")
    sc.add_argument("--threshold-s", dest="threshold_s", type=float,
                    default=10.0,
                    help="slow-marker threshold recorded by "
                    "--update-timings (default %(default)s)")


def cmd_gameday(args) -> int:
    """``gameday --out DIR`` — the production gameday
    (docs/RESILIENCE.md §Gameday): drive the composed system — trainer
    snapshotting under ``--resume auto``, replicated serving tier with
    live-obs + remediation + snapshot/index watching, the watch
    evaluator — through one deterministic compressed day of traffic
    while the chaos schedule injects every scripted fault, then write
    the ``npairloss-gameday-v1`` verdict to ``<out>/gameday.json``.
    Exit 0 iff the verdict passes (the jax-free twin:
    ``scripts/bench_check.py --gameday``)."""
    if args.duration <= 0:
        log.error("--duration must be > 0, got %s", args.duration)
        return 1
    scenario = getattr(args, "scenario", "day")
    if scenario == "day" and args.replicas < 2:
        log.error("--replicas must be >= 2 (the replica-crash entry "
                  "needs a survivor to reroute to), got %s",
                  args.replicas)
        return 1
    if args.schedule and scenario != "day":
        log.error("--schedule is the day scenario's knob; tenant_skew "
                  "ships its own schedule (the hot-tenant burst)")
        return 1
    if args.schedule and not os.path.exists(args.schedule):
        log.error("--schedule not found: %s", args.schedule)
        return 1

    from npairloss_tpu.gameday.runner import (GamedayError, run_gameday,
                                              run_tenant_skew)

    try:
        if scenario == "tenant_skew":
            report = run_tenant_skew(
                args.out, seed=args.seed, duration_s=args.duration,
                replicas=args.replicas)
        else:
            report = run_gameday(
                args.out, seed=args.seed, duration_s=args.duration,
                schedule_path=args.schedule, replicas=args.replicas)
    except GamedayError as e:
        log.error("gameday run broke: %s", e)
        return 1
    print(json.dumps({
        "verdict": report["verdict"],
        "failures": report["failures"],
        "faults": len(report["faults"]),
        "hot_swaps": report["zero_drop"]["hot_swaps"],
        "queries_dropped": report["zero_drop"]["queries_dropped"],
        "answered": report["traffic"]["answered"],
        "report": os.path.join(os.path.abspath(args.out),
                               "gameday.json"),
    }))
    return 0 if report["verdict"] == "pass" else 1


def cmd_staticcheck(args) -> int:
    """``staticcheck [ROOT]`` — the repo-wide invariant linter
    (docs/STATICCHECK.md): jax-free purity proofs for the contract
    modules, collective comm-scope coverage, guarded-by lock
    discipline, versioned-contract drift, vocabulary drift, and
    tier-1 marker discipline — failing in milliseconds at lint time
    what the runtime gates can only catch after the fact.  Jax-free
    end to end: runnable in a venv with no accelerator stack (the
    package import is lazy; this function imports only
    ``npairloss_tpu.analysis``)."""
    from npairloss_tpu.analysis.runner import run_from_args

    return run_from_args(args, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def cmd_parse(args) -> int:
    from npairloss_tpu.config import dumps, parse_file

    msg = parse_file(args.file)
    if args.json:
        print(json.dumps(msg.to_dict(), indent=2, default=str))
    else:
        print(dumps(msg))
    return 0


def _time_stage_bodies(solver, images, labels):
    """Scan bodies for the three timed stages of ``cmd_time`` plus the
    shared carry, built on the Solver's own apply_model/compute_loss
    plumbing (mutable batch stats threaded through the carry), so the
    differenced loss/backward shares compare like with like and the
    benchmarked graph IS the trained graph.  Two timing-integrity rules
    shape the bodies (regression-pinned by a FLOPs-ratio test):
      * every stage output is anchored by a WHOLE-tensor reduction
        (sum of emb / loss AND metrics / sum over ALL grad leaves) —
        anchoring a single element would let XLA dead-code-eliminate
        most of the work it claims to time (slice-through-dot narrows
        the final matmul; unconsumed grad leaves drop their weight-grad
        gemms; unconsumed metrics drop the retrieval subgraph);
      * params/images/labels ride the scan carry, not the closure —
        jit bakes captured arrays into each program as constants
        (three private copies of a ~72 MB flagship batch otherwise).
    Solver state must be initialized.  Returns
    ``(trunk_body, forward_body, fb_body, init_carry)``.
    """
    import jax
    import jax.numpy as jnp

    state = solver.state
    params, bstats = state["params"], state["batch_stats"]

    def _f32sum(x):
        return jnp.sum(x.astype(jnp.float32))

    def _anchor_all(loss, metrics):
        return jax.tree_util.tree_reduce(
            lambda a, v: a + _f32sum(v), metrics, loss.astype(jnp.float32)
        )

    def trunk_body(carry, s):
        acc, pp, bs, im, lb = carry
        emb, bs = solver.apply_model(
            pp, bs, im * (1.0 + s * 1e-6), train=True
        )
        return (acc + _f32sum(emb), pp, bs, im, lb)

    def forward_body(carry, s):
        acc, pp, bs, im, lb = carry
        emb, bs = solver.apply_model(
            pp, bs, im * (1.0 + s * 1e-6), train=True
        )
        loss, metrics = solver.compute_loss(emb, lb)
        return (acc + _anchor_all(loss, metrics) + _f32sum(emb),
                pp, bs, im, lb)

    def fb_body(carry, s):
        acc, pp, bs, im, lb = carry

        def loss_fn(p):
            emb, new_bs = solver.apply_model(
                p, bs, im * (1.0 + s * 1e-6), train=True
            )
            loss, metrics = solver.compute_loss(emb, lb)
            return loss, (metrics, new_bs)

        (loss, (metrics, new_bs)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(pp)
        gsum = jax.tree_util.tree_reduce(
            lambda a, g: a + _f32sum(g), grads, jnp.float32(0.0)
        )
        return (acc + _anchor_all(loss, metrics) + gsum, pp, new_bs, im, lb)

    init = (jnp.float32(0.0), params, bstats,
            jnp.asarray(images), jnp.asarray(labels))
    return trunk_body, forward_body, fb_body, init


def cmd_time(args) -> int:
    """The ``caffe time`` counterpart (the reference's implied Caffe fork
    is driven by the stock Caffe CLI, whose ``time`` action benchmarks a
    net's forward/backward from ``-model`` + ``-iterations`` alone —
    SURVEY.md §1 L1).  Caffe reports per-layer wall-clock; under jit the
    step is ONE fused XLA program, so the honest analog is per-STAGE
    attribution by differential timing: trunk forward, full forward
    (trunk + loss + metrics), and forward+backward, each measured with
    the fetch-synced scan discipline (docs/DESIGN.md §6) and differenced
    for the loss/backward shares."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from npairloss_tpu.data import synthetic_identity_batches
    from npairloss_tpu.utils.profiling import (
        dispatch_floor,
        mfu_from_timing,
        time_scan,
    )

    built = _build_solver(args)
    if isinstance(built, int):
        return built
    solver, net_cfg, input_shape = built

    # Batch geometry from the net's data layer (either phase), exactly
    # what `caffe time` would allocate; --batch/--ids override.
    for flag in ("ids", "batch"):
        v = getattr(args, flag, None)
        if v is not None and v < 1:
            log.error("--%s must be >= 1, got %d", flag, v)
            return 2
    d = net_cfg.data.get("TRAIN") or net_cfg.data.get("TEST")
    ids, imgs = _identity_batch_geometry(d)
    if args.ids:
        ids = args.ids
    elif args.batch:
        ids = max(args.batch // imgs, 1)
        if ids * imgs != args.batch:
            log.warning(
                "--batch %d is not a multiple of %d images/identity; "
                "timing batch %d", args.batch, imgs, ids * imgs,
            )
    images, labels = next(
        synthetic_identity_batches(ids * 4, ids, imgs, input_shape, seed=0)
    )
    images = jnp.asarray(images)
    labels = jnp.asarray(labels)
    batch = int(images.shape[0])

    if solver.state is None:
        solver.init(np.asarray(images[:2]))
    steps = int(args.iterations)
    if steps < 1:
        log.error("--iterations must be >= 1, got %d", steps)
        return 2
    floor = dispatch_floor()
    dev = jax.devices()[0]
    log.info("timing on %s (%s), batch %d, %d iterations",
             dev.platform, dev.device_kind, batch, steps)

    trunk_body, forward_body, fb_body, init = _time_stage_bodies(
        solver, images, labels
    )
    trunk_ms = time_scan(trunk_body, init, steps=steps, floor=floor)
    forward_ms = time_scan(forward_body, init, steps=steps, floor=floor)
    fb_ms = (None if args.forward_only else
             time_scan(fb_body, init, steps=steps, floor=floor))

    rec = {
        "device": f"{dev.platform}:{dev.device_kind}",
        "engine": solver.engine or "dense",
        "mesh_devices": solver.mesh.size if solver.mesh is not None else 1,
        "batch": batch,
        "iterations": steps,
        "fetch_floor_ms": round(floor * 1e3, 2),
        "trunk_forward_ms": round(trunk_ms, 3),
        "forward_ms": round(forward_ms, 3),
        "loss_forward_ms": round(max(forward_ms - trunk_ms, 0.0), 3),
    }
    if fb_ms is not None:
        rec["forward_backward_ms"] = round(fb_ms, 3)
        rec["backward_ms"] = round(max(fb_ms - forward_ms, 0.0), 3)
        rec["emb_per_sec"] = round(batch / fb_ms * 1e3, 1)
        # XLA's analytic FLOPs for one step, from the LOWERED program
        # (client-side; never asks the backend to compile a second
        # executable), plus MFU when the device's peak is known — both
        # via THE shared helper (obs.perf.costs.mfu_from_timing).
        try:
            lowered = jax.jit(
                lambda c: fb_body(c, jnp.float32(0.0))
            ).lower(init)
            est = mfu_from_timing(lowered, seconds=fb_ms * 1e-3,
                                  device_kind=dev.device_kind)
        except Exception as e:
            log.info("step_flops estimate unavailable: %s", e)
            est = {"step_flops": None, "mfu": None}
        if est["step_flops"]:
            rec["step_flops"] = est["step_flops"]
            if est["mfu"] is not None:
                rec["mfu"] = round(est["mfu"], 4)
    print(json.dumps(rec))
    return 0


def cmd_device_query(args) -> int:
    """The ``caffe device_query`` counterpart: enumerate the
    accelerator(s) the way ``caffe device_query -gpu N`` prints CUDA
    device properties (stock-Caffe CLI surface of the implied fork,
    SURVEY.md §1 L1) — platform, device kind, per-device memory
    stats, and the process/mesh topology that replaces
    ``Caffe::NUM_GPU``/``RANK`` (reference:
    npair_multi_class_loss.cpp:44)."""
    import jax

    devices = []
    for dv in jax.devices():
        mem = {}
        try:
            mem = dv.memory_stats() or {}
        except Exception:  # backends without memory introspection
            mem = {}
        devices.append({
            "id": dv.id,
            "platform": dv.platform,
            "device_kind": dv.device_kind,
            "process_index": dv.process_index,
            "bytes_in_use": mem.get("bytes_in_use"),
            "bytes_limit": mem.get("bytes_limit"),
        })
    print(json.dumps({
        "device_count": jax.device_count(),
        "local_device_count": jax.local_device_count(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "default_backend": jax.default_backend(),
        "devices": devices,
    }, indent=2))
    return 0


def cmd_prof(args) -> int:
    """Perf observatory (docs/OBSERVABILITY.md §Perf): one on-disk
    report per run — static per-``named_scope``-region FLOPs / bytes /
    arithmetic-intensity / roofline bound-class attribution of the
    jitted step, plus the span-derived step-time decomposition
    reconciled against wall time.  Device-trace-free by design
    (``jax.profiler`` wedges tunneled backends); everything comes from
    compiled-HLO metadata and the host span streams, so it runs
    anywhere — including CPU, where the roofline falls back to the v4
    reference spec (flagged in the report).

    ``--fleet RUNDIR`` is the OFFLINE mode (docs/OBSERVABILITY.md
    §Fleet observatory): aggregate a fleet run directory's per-rank
    telemetry streams into the ``npairloss-fleet-report-v1``
    straggler/skew/comms report plus one merged Perfetto timeline —
    no backend is touched.  ``--quality RUNDIR`` is its quality-
    observatory sibling: validate and render the run's
    ``npairloss-quality-v1`` shadow-recall log against its committed
    baseline (§Quality observatory; backend-free too)."""
    if getattr(args, "fleet", None):
        return _prof_fleet(args)
    if getattr(args, "quality", None):
        return _prof_quality(args)

    import jax
    import numpy as np

    from npairloss_tpu.obs import RunTelemetry
    from npairloss_tpu.obs import perf as obsperf

    steps = max(int(args.steps), 1)
    out_dir = args.out if args.out is not None else "perf_reports"
    dev = jax.devices()[0]
    tel = RunTelemetry(os.path.join(out_dir, "run"), metrics=True,
                       trace=True)
    try:
        if args.step == "train":
            report = _prof_train(args, jax, np, dev, tel, steps, obsperf)
        else:
            report = _prof_serve(args, jax, np, dev, tel, steps, obsperf)
    finally:
        tel.close()
    err = obsperf.validate_report(report)
    if err is not None:
        log.error("perf report failed its own schema check: %s", err)
        return 1
    paths = obsperf.write_report(report, out_dir)
    print(obsperf.render_table(report))
    print(json.dumps({"report": paths["json"], "table": paths["txt"],
                      "telemetry": tel.run_dir}))
    return 0


def _prof_quality(args) -> int:
    """``prof --quality RUNDIR``: offline quality-observatory report
    (docs/OBSERVABILITY.md §Quality observatory).  Validates the run's
    ``quality.jsonl`` against the ``npairloss-quality-v1`` contract,
    prints the per-window recall trend with the committed parity
    baseline alongside, and exits non-zero on a schema-invalid log —
    the validator is the contract, exactly like the perf/fleet paths.
    Stdlib-only: no backend is touched."""
    from npairloss_tpu.obs.quality import (
        load_quality_report,
        quality_breaches,
        quality_summary,
        stale_shadow,
        validate_quality_report,
    )

    run_dir = os.path.abspath(args.quality)
    path = (run_dir if run_dir.endswith(".jsonl")
            else os.path.join(run_dir, "quality.jsonl"))
    if not os.path.exists(path):
        log.error("prof --quality: no quality log at %s (serve with "
                  "--shadow-rate > 0 to produce one)", path)
        return 2
    records = load_quality_report(path)
    err = validate_quality_report(records)
    if err is not None:
        log.error("quality log failed its own schema check: %s", err)
        return 1
    summary = quality_summary(records)
    lines = [f"quality observatory — {path}",
             f"  windows {summary['windows']}, samples "
             f"{summary['sampled_total']}, shadow rate "
             f"{summary['shadow_rate']:g}"]
    for key, row in sorted(summary.get("recall", {}).items()):
        lines.append(
            f"  recall@{key[3:]}: min {row['min']:.4f}  mean "
            f"{row['mean']:.4f}  last {row['last']:.4f}")
    base = summary.get("baseline")
    if base:
        lines.append(f"  committed baseline (probes {base.get('probes')},"
                     f" sample {base.get('sample')}): "
                     + json.dumps(base.get("recall", {})))
    if "recall_floor" in summary:
        lines.append(f"  declared floor: {summary['recall_floor']:g} on "
                     f"{summary['floor_metric']} — "
                     f"{summary['breaches']} breaching window(s)")
    for i, metric, r, floor in quality_breaches(records):
        lines.append(f"    breach: record {i} {metric} {r:.4f} < "
                     f"{floor:g}")
    stale = stale_shadow(records)
    if stale:
        lines.append(f"  WARNING: {stale}")
    print("\n".join(lines))
    print(json.dumps({"log": path, **summary,
                      **({"stale": stale} if stale else {})}))
    return 0


def _prof_fleet(args) -> int:
    """``prof --fleet RUNDIR``: offline fleet aggregation (stdlib-only
    — never touches a backend; the streams on disk are the input).
    Writes ``fleet_report.json``/``.txt`` and the merged
    ``fleet_trace.json`` to --out (default: the run dir itself), prints
    the table, and fails on a schema-invalid report — the validator is
    the contract, exactly like the perf report path."""
    from npairloss_tpu.obs.fleet import (
        build_fleet_report,
        merge_run_traces,
        render_fleet_table,
        validate_fleet_report,
        write_fleet_report,
    )
    from npairloss_tpu.obs.tracing import validate_chrome_trace

    run_dir = os.path.abspath(args.fleet)
    if not os.path.isdir(run_dir):
        log.error("prof --fleet: %s is not a directory", run_dir)
        return 2
    # --out default is None (a sentinel, not the literal "perf_reports"
    # string) so an EXPLICIT --out perf_reports is honored here too.
    out_dir = args.out if args.out is not None else run_dir
    os.makedirs(out_dir, exist_ok=True)
    report = build_fleet_report(run_dir)
    trace_path, merged = merge_run_traces(
        run_dir, os.path.join(out_dir, "fleet_trace.json")
        if os.path.abspath(out_dir) != run_dir else None)
    if trace_path is not None:
        terr = validate_chrome_trace(merged)
        if terr is not None:
            # The report itself is independent evidence — land it
            # before failing, same as the schema-failure branch below.
            write_fleet_report(report, out_dir)
            log.error("merged fleet trace failed validation: %s", terr)
            return 1
        report.setdefault("notes", []).append(
            f"merged timeline: {trace_path} "
            f"({len(merged['traceEvents'])} events, "
            f"{len(merged['otherData']['merged_ranks'])} rank lane(s))")
    err = validate_fleet_report(report)
    if err is not None:
        # The report (with its failure) still lands on disk — a bad
        # fleet state must be diagnosable from artifacts too.
        write_fleet_report(report, out_dir)
        log.error("fleet report failed its own schema check: %s", err)
        return 1
    paths = write_fleet_report(report, out_dir)
    print(render_fleet_table(report))
    print(json.dumps({"report": paths["json"], "table": paths["txt"],
                      "trace": trace_path}))
    return 0


def _prof_train(args, jax, np, dev, tel, steps, obsperf):
    """Train-step profile: N real solver steps (device-wait spanned so
    device compute is attributed, not absorbed), then one extra AOT
    compile of the same program for its HLO text."""
    import time as _time

    import jax.numpy as jnp

    from npairloss_tpu import REFERENCE_CONFIG
    from npairloss_tpu.models import get_model
    from npairloss_tpu.train import Solver, SolverConfig

    batch = int(args.batch)
    side = int(args.image)
    policy = getattr(args, "precision", None)
    if policy:
        model = get_model(args.model, policy=policy)
    else:
        model = get_model(
            args.model, dtype=jnp.bfloat16 if args.bf16 else jnp.float32)
    mesh = None
    if args.mesh and args.mesh > 1:
        from npairloss_tpu.parallel import data_parallel_mesh

        mesh = data_parallel_mesh(jax.devices()[:args.mesh])
    input_shape = (side, side, 3) if args.model != "mlp" else (side,)
    solver = Solver(
        model, REFERENCE_CONFIG,
        SolverConfig(base_lr=0.001, lr_policy="step", stepsize=10000,
                     gamma=0.5, momentum=0.9, weight_decay=2e-5,
                     display=0, snapshot=0),
        # perf_metrics stays OFF: with display=0 the continuous rows
        # never emit, so the flops capture would only pay an extra
        # client-side re-lowering (~1/3 of a small prof run's wall)
        # that the report doesn't consume — build_report reads the
        # compiled stage directly.
        mesh=mesh, engine=args.engine, input_shape=input_shape,
        precision=policy or None,
        telemetry=tel,
    )
    # The shared synthetic generator, not a hand-rolled batch — the
    # identity-pair layout contract lives in data.synthetic only.
    from npairloss_tpu.data import synthetic_identity_batches

    ids = max((batch + 1) // 2, 1)
    x, lab = next(iter(synthetic_identity_batches(
        ids, ids, 2, input_shape, seed=0)))
    x, lab = x[:batch], lab[:batch]
    log.info("prof train: model=%s batch=%d steps=%d device=%s",
             args.model, batch, steps, dev.device_kind)
    solver.init(x[:2])
    t0_us = tel.tracer.now_us()
    step_walls = []
    t0 = _time.perf_counter()
    for i in range(steps):
        s0 = _time.perf_counter()
        metrics = solver.step(x, lab)
        # The dispatch is async: without this span the device compute
        # would land in "unattributed"; with it, the wait IS the
        # device-compute share of the loop wall clock.
        with tel.span("step/device_wait", step=i):
            jax.block_until_ready(metrics)
        step_walls.append(_time.perf_counter() - s0)
    wall_ms = (_time.perf_counter() - t0) * 1e3
    # Post-compile per-step time: the first step paid the XLA compile.
    warm = step_walls[1:] or step_walls
    ms_per_step = min(warm) * 1e3
    log.info("prof train: %d steps in %.1f ms (%.2f ms/step warm); "
             "extracting HLO (one extra AOT compile)...",
             steps, wall_ms, ms_per_step)
    x_sds = jax.ShapeDtypeStruct((batch, *input_shape), jnp.float32)
    lab_sds = jax.ShapeDtypeStruct((batch,), jnp.int32)
    compiled = solver._step_fn.lower(solver.state, x_sds, lab_sds).compile()
    events = [e for e in tel.tracer.to_chrome_trace()["traceEvents"]
              if e.get("ts", 0) >= t0_us]
    return obsperf.build_report(
        step="train", device_kind=dev.device_kind, batch=batch,
        stage=compiled, span_events=events, wall_ms=wall_ms,
        ms_per_step=ms_per_step, steps=steps,
        region_depth=int(args.region_depth),
        extra={"model": args.model, "engine": solver.engine,
               "policy": policy or None,
               # The satellite of --dump-partitions: a prof'd mesh run
               # stamps the same rule digest, so a silent no-op rule is
               # visible in the perf artifact too.
               **({"partition": solver.partition_summary()}
                  if mesh is not None else {})},
    )


def _prof_serve(args, jax, np, dev, tel, steps, obsperf):
    """Serve-query profile: synthetic gallery + warmed QueryEngine, N
    per-bucket query dispatches, static attribution of the largest
    bucket's top-k program, serve/* span latency split."""
    import time as _time

    import jax.numpy as jnp

    from npairloss_tpu.serve import EngineConfig, GalleryIndex, QueryEngine

    rng = np.random.default_rng(0)
    gallery = int(args.gallery)
    dim = int(args.dim)
    emb = rng.standard_normal((gallery, dim)).astype(np.float32)
    index = GalleryIndex.build(
        emb, (np.arange(gallery) % max(gallery // 8, 1)).astype(np.int32))
    buckets = tuple(int(b) for b in args.buckets.split(","))
    engine = QueryEngine(
        index, EngineConfig(top_k=int(args.top_k), buckets=buckets),
        telemetry=tel,
    )
    log.info("prof serve: gallery=%d dim=%d buckets=%s steps=%d",
             gallery, dim, buckets, steps)
    engine.warmup()
    t0_us = tel.tracer.now_us()
    t0 = _time.perf_counter()
    q = rng.standard_normal((buckets[-1], dim)).astype(np.float32)
    # Cycle largest-bucket-first so every bucket contributes spans to
    # the latency split, but time ONLY the largest bucket's own
    # dispatches: the MFU/emb_per_sec line prices the largest bucket's
    # compiled program, and dividing its FLOPs by a wall averaged over
    # smaller batches would inflate both by the bucket-size spread.
    big_walls = []
    for i in range(steps):
        b = buckets[-1 - (i % len(buckets))]
        s0 = _time.perf_counter()
        engine.query(q[:b])
        if b == buckets[-1]:
            big_walls.append(_time.perf_counter() - s0)
    wall_ms = (_time.perf_counter() - t0) * 1e3
    bucket = buckets[-1]
    qpad = jnp.zeros((bucket, dim), jnp.float32)
    compiled = engine._topk_fn.lower(
        qpad, index.emb, index.labels, index.valid).compile()
    events = [e for e in tel.tracer.to_chrome_trace()["traceEvents"]
              if e.get("ts", 0) >= t0_us]
    return obsperf.build_report(
        step="serve", device_kind=dev.device_kind, batch=bucket,
        stage=compiled, span_events=events, wall_ms=wall_ms,
        ms_per_step=min(big_walls) * 1e3, steps=len(big_walls),
        serve_spans=True,
        region_depth=int(args.region_depth),
        extra={"gallery": gallery, "dim": dim,
               "compile_stats": engine.compile_stats()},
    )


def cmd_bench(args) -> int:
    import importlib.util

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(repo_root, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    # Forward only the subcommand's own args — bench.main would
    # otherwise re-parse the full argv (incl. the word "bench") and die.
    bench.main(list(args.bench_args or []))
    return 0


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="npairloss_tpu", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument(
        "--platform", choices=["default", "cpu"], default="default",
        help="force the jax platform BEFORE backend init via "
        "jax.config.update (more robust than the JAX_PLATFORMS env var: "
        "when a remote TPU plugin's tunnel is unreachable, env-var "
        "forcing still hangs in plugin discovery, the config path "
        "does not)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("train", help="train from a solver prototxt")
    t.add_argument("--solver", required=True)
    t.add_argument("--net", help="override the solver's net path")
    t.add_argument("--model", help="model registry name (default: from net)")
    t.add_argument("--max_iter", type=int, help="override solver max_iter")
    t.add_argument("--mesh", type=int, help="devices in the dp mesh")
    t.add_argument(
        "--engine", choices=["auto", "dense", "ring", "blockwise"],
        help="loss engine (default: dense; ring streams the pool over a "
        "mesh, blockwise streams Pallas tiles on one device; auto picks "
        "dense vs ring from the mesh's host topology and the roofline "
        "ICI/DCN peaks — the plan lands in the run manifest)",
    )
    t.add_argument(
        "--mp", type=int, default=1, metavar="M",
        help="model-parallel axis size: the mesh becomes 2-D (dp x mp) "
        "with mp groups on adjacent (same-host) chips, for partition "
        "rules that shard parameters over 'mp' (docs/DISTRIBUTED.md)",
    )
    t.add_argument(
        "--partition-rules", dest="partition_rules", metavar="FILE",
        help="JSON partition-rule table: ordered [regex, spec] pairs "
        "over the flattened state-tree path, first match wins, "
        "unmatched leaves are a loud error (default: everything "
        "replicated) — docs/DISTRIBUTED.md cookbook",
    )
    t.add_argument(
        "--dump-partitions", dest="dump_partitions", action="store_true",
        help="print the resolved rule->PartitionSpec table per state "
        "leaf (zero-match rules flagged) before training; pair with "
        "--max_iter 0 as a preflight check",
    )
    t.add_argument(
        "--pos-topk", dest="pos_topk", default="auto", metavar="K",
        type=_pos_topk_arg,
        help="streaming engines' sparse-positive buffer slots for "
        "RELATIVE AP mining (auto = 8; 0 forces radix selection)")
    t.add_argument(
        "--sim-cache", dest="sim_cache", choices=["auto", "on", "off"],
        default="auto",
        help="streaming engines' fp32 similarity cache (auto = by size)",
    )
    t.add_argument(
        "--matmul-precision", dest="matmul_precision",
        choices=["highest", "default"], default=None,
        help="loss-engine gemm precision: highest = oracle bit-parity "
        "(default), default = ~6x single-pass bf16 MXU throughput mode",
    )
    t.add_argument("--bf16", action="store_true", help="bfloat16 trunk")
    t.add_argument(
        "--precision", choices=_PRECISION_CHOICES, default=None,
        help="declarative mixed-precision policy (models.precision): "
        "mxu = the flagship default (bf16 compute over fp32 params, "
        "single-pass bf16 MXU gemms incl. the loss engines), bf16 = "
        "the legacy --bf16 recipe as a named policy, fp32_parity = the "
        "prototxt-parity fp32 fallback; overrides --bf16 and supplies "
        "--matmul-precision's default",
    )
    t.add_argument(
        "--remat", action="store_true",
        help="rematerialize inception blocks in the backward (GoogLeNet "
        "trunks): ~25%% more trunk FLOPs for much lower activation HBM "
        "— lifts the per-chip batch ceiling; numerically identical",
    )
    t.add_argument(
        "--resume",
        help="snapshot path to restore, or 'auto' to scan snapshot_prefix "
        "for the newest valid snapshot (torn/corrupt ones skipped with a "
        "logged reason; none found = fresh start) — the supervisor-"
        "relaunch contract, docs/RESILIENCE.md",
    )
    t.add_argument(
        "--weights",
        help="pretrained params (.msgpack from import-caffemodel) to "
        "finetune from — fresh optimizer state, iteration 0 (use "
        "--resume for mid-training snapshots instead)",
    )
    t.add_argument(
        "--caffe-solverstate", dest="caffe_solverstate", metavar="PATH",
        help="resume the optimizer (momentum + iteration) from a Caffe "
        ".solverstate — the `caffe train --snapshot` semantics; pair "
        "with --weights for the matching .caffemodel parameters",
    )
    t.add_argument("--snapshot_prefix", help="override snapshot prefix")
    t.add_argument(
        "--snapshot-keep", dest="snapshot_keep", type=int, metavar="N",
        help="retention GC: keep only the newest N committed snapshots "
        "(default: solver snapshot_max_keep; 0 keeps all)",
    )
    t.add_argument(
        "--divergence-patience", dest="divergence_patience", type=int,
        default=0, metavar="N",
        help="arm the divergence guard: N consecutive non-finite losses "
        "trigger --divergence-action (0 = off; costs one host sync per "
        "step when armed)",
    )
    t.add_argument(
        "--divergence-action", dest="divergence_action",
        choices=["rollback", "halt"], default="rollback",
        help="guard action: rollback restores the newest valid snapshot "
        "(bounded by --divergence-max-rollbacks), halt stops with a "
        "diagnosis",
    )
    t.add_argument(
        "--divergence-lr-scale", dest="divergence_lr_scale", type=float,
        default=1.0, metavar="S",
        help="multiply base_lr by S on each rollback (e.g. 0.5 halves "
        "the lr so the trajectory doesn't re-diverge)",
    )
    t.add_argument(
        "--divergence-max-rollbacks", dest="divergence_max_rollbacks",
        type=int, default=2, metavar="N",
        help="rollbacks allowed before the guard halts anyway",
    )
    t.add_argument(
        "--pipeline", action="store_true",
        help="sync-free stepping (docs/PIPELINE.md): device-resident "
        "double-buffered batch prefetch, per-step scalars accumulated "
        "in a device-side ring and read back only at display/test/"
        "snapshot window boundaries, dispatch depth bounded — the "
        "device never waits on the host in steady state; parity-pinned "
        "bit-identical to the default loop",
    )
    t.add_argument(
        "--pipeline-depth", dest="pipeline_depth", type=int, default=2,
        metavar="K",
        help="prefetch depth AND max in-flight dispatched steps "
        "(default 2 — double buffering)",
    )
    t.add_argument(
        "--pipeline-window", dest="pipeline_window", type=int, default=0,
        metavar="W",
        help="cap on steps between host syncs (0 = auto: the smallest "
        "active display/test/snapshot cadence, else 64); bounds the "
        "divergence guard's detection staleness",
    )
    t.add_argument(
        "--compile-cache", dest="compile_cache", metavar="DIR",
        help="persistent XLA compilation cache directory: programs "
        "compiled by ANY process land here, so reruns and sibling "
        "processes deserialize instead of recompiling (the batch-480 "
        "flagship compile ran 25 minutes — pay it once)",
    )
    t.add_argument(
        "--no-preempt-handler", dest="no_preempt_handler",
        action="store_true",
        help="do not install the SIGTERM/SIGINT graceful-preemption "
        "handler (emergency snapshot + exit 75)",
    )
    t.add_argument(
        "--synthetic", action="store_true",
        help="train on synthetic identity-balanced clusters instead of the "
        "net's data source (required opt-in; a missing source is an error)",
    )
    t.add_argument(
        "--native", choices=["auto", "never", "require"], default="auto",
        help="C++ data runtime routing: auto (by source suffixes), never "
        "(Python/PIL pipeline), require (error if the native runtime "
        "cannot serve this source)",
    )
    t.add_argument(
        "--caffe-pad", dest="caffe_pad", action="store_true",
        help="evaluate conv1 at Caffe's exact pad-3 geometry (GoogLeNet "
        "trunks; use with imported .caffemodel weights — SAME samples a "
        "phase-shifted grid at stride 2)",
    )
    t.add_argument(
        "--coordinator",
        help="multi-process coordinator HOST:PORT (the mpirun counterpart); "
        "omit on TPU pods for autodetect",
    )
    t.add_argument(
        "--log-json", dest="log_json", metavar="PATH",
        help="append one JSON record per display/test/snapshot event "
        "(machine-readable counterpart of the Caffe-style text log)",
    )
    t_tel = t.add_mutually_exclusive_group()
    t_tel.add_argument(
        "--telemetry-dir", dest="telemetry_dir", metavar="DIR",
        help="full run-telemetry directory: manifest.json (config/topology/"
        "git-sha snapshot) + metrics.jsonl (one structured row per train "
        "step and eval) + trace.json (host span timeline, Perfetto-"
        "viewable) — see docs/OBSERVABILITY.md",
    )
    t_tel.add_argument(
        "--trace-dir", dest="trace_dir", metavar="DIR",
        help="host-side span tracing only: write DIR/trace.json "
        "(Chrome-trace JSON) without per-step metric rows (and without "
        "their per-step host sync); mutually exclusive with "
        "--telemetry-dir, whose run dir already includes the trace",
    )
    t.add_argument(
        "--fleet", action="store_true",
        help="force rank-stamped fleet telemetry (telemetry.r<k>.jsonl "
        "per rank, comm accounting, step-numbered spans) even on a "
        "single process; multi-process runs stamp automatically — "
        "docs/OBSERVABILITY.md §Fleet observatory",
    )
    t.add_argument(
        "--health-metrics", dest="health_metrics", action="store_true",
        help="fold in-graph training-health signals into every step's "
        "metrics (grad/param/update norms, update/param ratio, embedding "
        "magnitude, mined-pair hardness) — obs.health.HealthConfig",
    )
    t.add_argument(
        "--mining-health", dest="mining_health", action="store_true",
        help="extend the health rows with mining-quality trend stats "
        "(AP-AN margin mean/p10, hard-negative saturation) from the "
        "same loss aux — embedding collapse as a quality trend "
        "(docs/OBSERVABILITY.md §Quality observatory); implies "
        "--health-metrics",
    )
    t.add_argument(
        "--perf-metrics", dest="perf_metrics", action="store_true",
        help="emit one phase=\"perf\" telemetry row per display window "
        "(ms_per_step, emb_per_sec, MFU from XLA's analytic step FLOPs) "
        "— needs --telemetry-dir; docs/OBSERVABILITY.md §Perf",
    )
    t.add_argument(
        "--live-obs", dest="live_obs", action="store_true",
        help="live observatory (docs/OBSERVABILITY.md §Live): feed this "
        "run's telemetry rows into the in-process metric registry, "
        "evaluate SLO watchdogs continuously, and append firing/resolved "
        "alerts to <telemetry-dir>/alerts.jsonl (npairloss-alerts-v1); "
        "needs --telemetry-dir; the telemetry streams on disk stay "
        "byte-identical",
    )
    t.add_argument(
        "--slo-config", dest="slo_config", metavar="PATH",
        help="SLO config (JSON; TOML on tomllib-equipped interpreters): "
        "watchdog presets by name plus explicit SLO entries — default: "
        "the standard train watchdogs",
    )
    t.add_argument(
        "--slo-tick", dest="slo_tick", type=float, default=1.0,
        metavar="S",
        help="live-obs evaluation period in seconds (default 1.0)",
    )
    t.add_argument(
        "--metrics-port", dest="metrics_port", type=int, metavar="PORT",
        help="with --live-obs: serve Prometheus /metrics (+ /healthz "
        "with SLO status) on this localhost port",
    )
    t.add_argument(
        "--remediate", action="store_true",
        help="alert→actuation (docs/RESILIENCE.md §Remediation): a "
        "health-signal alert (embedding collapse) requests a rollback "
        "to a pre-incident snapshot, executed at the loop's next safe "
        "point and audited to <telemetry-dir>/remediation.jsonl; "
        "needs --live-obs",
    )
    t.add_argument(
        "--remediation-config", dest="remediation_config",
        metavar="PATH",
        help="remediation policy table (JSON; default: the shipped "
        "train policies)",
    )
    t.add_argument(
        "--remediate-dry-run", dest="remediate_dry_run",
        action="store_true",
        help="log every remediation the policies WOULD run without "
        "acting — implies --remediate",
    )
    t.add_argument(
        "--debug-checks", dest="debug_checks", action="store_true",
        help="validate every step's loss/metric scalars are finite on "
        "host (utils.debug.enable_debug_checks; also settable via "
        "NPAIRLOSS_DEBUG_CHECKS=1)",
    )
    t.add_argument("--num-processes", type=int, help="total host processes")
    t.add_argument("--process-id", type=int, help="this process's rank")
    t.set_defaults(fn=cmd_train)

    def _common(sp):
        sp.add_argument("--solver", required=True)
        sp.add_argument("--net", help="override the solver's net path")
        sp.add_argument("--model", help="model registry name")
        sp.add_argument("--mesh", type=int, help="devices in the dp mesh")
        sp.add_argument(
            "--engine", choices=["dense", "ring", "blockwise"],
            help="loss engine (see train --engine)",
        )
        sp.add_argument(
            "--sim-cache", dest="sim_cache", choices=["auto", "on", "off"],
            default="auto", help="see train --sim-cache",
        )
        sp.add_argument("--bf16", action="store_true")
        sp.add_argument(
            "--precision", choices=_PRECISION_CHOICES, default=None,
            help="mixed-precision policy (see train --precision)",
        )
        sp.add_argument(
            "--resume",
            help="snapshot path to restore, or 'auto' for the newest "
            "valid one under snapshot_prefix (see train --resume)",
        )
        sp.add_argument("--synthetic", action="store_true")
        sp.add_argument(
            "--native", choices=["auto", "never", "require"],
            default="auto", help="see train --native",
        )
        sp.add_argument(
            "--caffe-pad", dest="caffe_pad", action="store_true",
            help="see train --caffe-pad",
        )

    tt = sub.add_parser(
        "test", help="TEST phase only from a snapshot (caffe test)"
    )
    _common(tt)
    tt.add_argument(
        "--iterations", type=int,
        help="TEST batches to average (default: solver test_iter)",
    )
    tt.set_defaults(fn=cmd_test)

    ex = sub.add_parser(
        "extract", help="dump embeddings + labels to .npy (eval mode)"
    )
    _common(ex)
    ex.add_argument("--phase", default="TEST", choices=["TEST", "TRAIN", "test", "train"])
    ex.add_argument("--batches", type=int, default=16)
    ex.add_argument("--out", default="./features")
    ex.set_defaults(fn=cmd_extract)

    ev = sub.add_parser(
        "eval",
        help="full-gallery Recall@K over extracted embeddings (.npy)",
    )
    ev.add_argument(
        "--prefix", default="./features",
        help="extract output prefix (reads PREFIX.emb.npy + "
        "PREFIX.labels.npy)",
    )
    ev.add_argument("--emb", help="explicit embeddings .npy path")
    ev.add_argument("--labels", help="explicit labels .npy path")
    ev.add_argument(
        "--ks", type=int, nargs="+", default=[1, 2, 4, 8, 16, 32],
        help="Recall@K cutoffs (CUB reports 1 2 4 8; SOP 1 10 100 1000)",
    )
    ev.add_argument(
        "--query-block", type=int, default=1024,
        help="queries per streamed block (the N x N matrix is never "
        "materialized)",
    )
    ev.add_argument(
        "--nmi", action="store_true",
        help="also report clustering NMI (on-device k-means with "
        "k = #classes — the CUB/SOP paper protocol's second number)",
    )
    ev.add_argument("--kmeans-iters", type=int, default=20)
    ev.set_defaults(fn=cmd_eval)

    ix = sub.add_parser(
        "index",
        help="build a committed gallery index from extracted embeddings",
    )
    ix.add_argument(
        "--prefix", default="./features",
        help="extract output prefix (reads PREFIX.emb.npy + "
        "PREFIX.labels.npy; default index path PREFIX.gidx)",
    )
    ix.add_argument("--emb", help="explicit embeddings .npy path")
    ix.add_argument("--labels", help="explicit labels .npy path")
    ix.add_argument("--out", help="index directory to commit (.gidx)")
    ix.add_argument(
        "--add-to", dest="add_to", metavar="INDEX",
        help="append rows to an existing index (incremental add) and "
        "re-commit it instead of building fresh",
    )
    ix.add_argument(
        "--no-normalize", dest="no_normalize", action="store_true",
        help="trust the rows are already unit-norm (extract output is)",
    )
    ix.add_argument(
        "--info", metavar="INDEX",
        help="print an existing index's manifest summary and exit",
    )
    ix.add_argument(
        "--kind", choices=["flat", "ivf"], default="flat",
        help="index structure: flat (exact brute-force scan — the "
        "recall oracle) or ivf (k-means clustered, probe-top-C "
        "approximate search; docs/SERVING.md §Approximate index)",
    )
    ix.add_argument(
        "--clusters", type=int, default=0,
        help="ivf cluster count (0 = ~sqrt(N), the classical balance "
        "point)",
    )
    ix.add_argument(
        "--kmeans-iters", dest="kmeans_iters", type=int, default=10,
        help="ivf k-means Lloyd iterations (default 10)",
    )
    ix.add_argument(
        "--train-sample", dest="train_sample", type=int, default=131072,
        help="ivf k-means training subsample bound (full assignment "
        "always streams the whole gallery; default 131072)",
    )
    ix.add_argument(
        "--parity-sample", dest="parity_sample", type=int, default=256,
        help="queries sampled for the build-time recall parity stamp "
        "in the ivf commit manifest (0 disables; default 256) — the "
        "live shadow-recall baseline (docs/OBSERVABILITY.md §Quality)",
    )
    ix.add_argument(
        "--parity-probes", dest="parity_probes", type=int, default=8,
        help="probe count the parity stamp measures at (match the "
        "serving --probes; default 8)",
    )
    ix.set_defaults(fn=cmd_index)

    sv = sub.add_parser(
        "serve",
        help="serve top-K retrieval queries against a gallery index "
        "(stdin/JSONL, or localhost HTTP with --http)",
    )
    sv_idx = sv.add_mutually_exclusive_group(required=True)
    sv_idx.add_argument("--index", help="committed index dir (.gidx)")
    sv_idx.add_argument(
        "--index-prefix", dest="index_prefix",
        help="scan PREFIX*.gidx newest-first and serve the first valid "
        "one (torn/corrupt indexes skipped with a logged reason)",
    )
    sv_idx.add_argument(
        "--tenant-config", dest="tenant_config", metavar="PATH",
        help="multi-tenant serving (docs/SERVING.md §Multi-tenant): a "
        "npairloss-tenants-v1 JSON manifest mapping tenant ids to "
        "index prefixes, per-tenant index kind/probe impl, qps quota, "
        "recall floor and admission params; every query/ingest record "
        "must carry a registered 'tenant' id, and freshness, quotas, "
        "SLOs and shadow scoring split per tenant behind one front "
        "end and one replica tier (replaces --index/--index-prefix)",
    )
    sv.add_argument(
        "--snapshot",
        help="training snapshot to restore for raw-'input' queries "
        "(embedding queries need no model)",
    )
    sv.add_argument("--model", help="model registry name for --snapshot")
    sv.add_argument(
        "--input-size", dest="input_size", type=int, default=224,
        help="input side length for the encode path (default 224)",
    )
    sv.add_argument(
        "--index-kind", dest="index_kind", choices=["flat", "ivf"],
        default="flat",
        help="serve the gallery flat (exact scan — the recall oracle) "
        "or through the IVF probe path; a flat commit served with ivf "
        "is clustered in-memory at startup (--ivf-clusters), an ivf "
        "commit served flat falls back to the exact scan",
    )
    sv.add_argument(
        "--ivf-clusters", dest="ivf_clusters", type=int, default=0,
        help="cluster count when building IVF at startup from a flat "
        "commit (0 = ~sqrt(N))",
    )
    sv.add_argument(
        "--probes", type=int, default=8,
        help="IVF clusters scored per query (recall-vs-latency knob; "
        "clamped to the cluster count; default 8)",
    )
    sv.add_argument(
        "--scoring", choices=["fp32", "bf16", "int8"], default="fp32",
        help="similarity-matmul dtype: fp32 (oracle precision), bf16 "
        "(half the scan bandwidth/MXU cost), int8 (IVF only: "
        "per-cluster-scale quantized slab) — gate reduced modes with "
        "the recall-parity harness (docs/SERVING.md)",
    )
    sv.add_argument(
        "--probe-impl", dest="probe_impl",
        choices=list(_PROBE_IMPL_CHOICES), default="scan",
        help="IVF probe-path implementation: 'scan' (the lax.scan "
        "gather+score baseline), 'fused' (single-pass Pallas kernel: "
        "gather + score + running top-k in one VMEM pass, in-kernel "
        "int8 dequant), 'auto' (fused on TPU, scan elsewhere); the "
        "resolved choice is stamped into the run manifest and /healthz "
        "(ignored by a flat index)",
    )
    sv.add_argument(
        "--replicas", type=int, default=1,
        help="QueryEngine replicas behind this front end (shared "
        "compiled programs; least-loaded routing; per-replica drain)",
    )
    sv.add_argument(
        "--admission", choices=["off", "slo"], default="off",
        help="admission control: 'slo' sheds load (fast-reject, "
        "counted in rejected) while a watched SLO burns and admits "
        "again on clear — needs --live-obs (docs/SERVING.md "
        "§Admission-control runbook)",
    )
    sv.add_argument(
        "--admission-slos", dest="admission_slos", metavar="NAMES",
        help="comma-separated SLO names driving admission (default "
        "serve_p99,serve_queue_saturation)",
    )
    sv.add_argument("--top-k", dest="top_k", type=int, default=10)
    sv.add_argument(
        "--buckets", default="1,8,32",
        help="ascending query padding buckets; steady state serves "
        "exactly these program shapes (default 1,8,32)",
    )
    sv.add_argument(
        "--deadline-ms", dest="deadline_ms", type=float, default=5.0,
        help="max added latency a query may wait for micro-batch "
        "co-riders (default 5)",
    )
    sv.add_argument(
        "--max-queue", dest="max_queue", type=int, default=256,
        help="admission queue bound; submits beyond it are rejected "
        "with backpressure (default 256)",
    )
    sv.add_argument(
        "--metrics-window", dest="metrics_window", type=int, default=100,
        help="queries per emitted latency/QPS/queue-depth metrics row "
        "(0 = none)",
    )
    sv.add_argument(
        "--poll-s", dest="poll_s", type=float, default=0.1,
        help="front-end wakeup period: how long an answer may sit "
        "ready before the idle flush emits it, and the drain-signal "
        "reaction bound while idle — lower it when measured latency "
        "at low qps matters more than wakeup overhead (default 0.1)",
    )
    sv.add_argument(
        "--gallery-block", dest="gallery_block", type=int, default=4096,
        help="gallery rows streamed per block inside a shard",
    )
    sv.add_argument("--mesh", type=int, help="devices in the dp mesh")
    sv.add_argument(
        "--http", type=int, metavar="PORT",
        help="serve localhost HTTP on PORT instead of stdin/JSONL",
    )
    sv.add_argument(
        "--no-warmup", dest="no_warmup", action="store_true",
        help="skip the per-bucket warmup (first queries then pay "
        "the compiles the warmup would have)",
    )
    sv.add_argument(
        "--compile-cache", dest="compile_cache", metavar="DIR",
        help="persistent XLA compilation cache (see train "
        "--compile-cache): replica restarts deserialize the warmed "
        "buckets instead of recompiling",
    )
    sv.add_argument(
        "--live-obs", dest="live_obs", action="store_true",
        help="live observatory (docs/OBSERVABILITY.md §Live): SLO "
        "watchdogs over the serve window rows, alerts.jsonl in the "
        "telemetry dir, /metrics + SLO-enriched /healthz on the --http "
        "front end; needs --telemetry-dir",
    )
    sv.add_argument(
        "--slo-config", dest="slo_config", metavar="PATH",
        help="SLO config (JSON/TOML) — default: the standard serve "
        "watchdogs (p99, queue saturation, post-warmup compiles, "
        "index/model staleness)",
    )
    sv.add_argument(
        "--slo-tick", dest="slo_tick", type=float, default=1.0,
        metavar="S",
        help="live-obs evaluation period in seconds (default 1.0)",
    )
    sv.add_argument(
        "--remediate", action="store_true",
        help="alert→actuation (docs/RESILIENCE.md §Remediation): bind "
        "the live alerts to guarded actions — snapshot/index hot-swap "
        "on staleness (needs --watch-snapshots/--index-prefix), "
        "load-shed on queue saturation, re-warm on a post-warmup "
        "compile storm — audited to remediation.jsonl; needs "
        "--live-obs",
    )
    sv.add_argument(
        "--remediation-config", dest="remediation_config",
        metavar="PATH",
        help="remediation policy table (JSON; default: the shipped "
        "serve policies filtered to the actions this invocation can "
        "perform)",
    )
    sv.add_argument(
        "--remediate-dry-run", dest="remediate_dry_run",
        action="store_true",
        help="log every remediation the policies WOULD run (budgets "
        "included) without acting — implies --remediate",
    )
    sv.add_argument(
        "--shadow-rate", dest="shadow_rate", type=float, default=0.0,
        metavar="FRAC",
        help="fraction of live queries shadow-scored off the hot path "
        "against the flat exact oracle (deterministic by query id) — "
        "emits live serve_recall_at_{1,5,10} + score-gap rows and the "
        "npairloss-quality-v1 log; 0 (default) disables and keeps "
        "every stream byte-identical; needs --telemetry-dir "
        "(docs/OBSERVABILITY.md §Quality observatory)",
    )
    sv.add_argument(
        "--shadow-window", dest="shadow_window", type=int, default=32,
        help="shadow samples per emitted quality window row "
        "(default 32)",
    )
    sv.add_argument(
        "--shadow-seed", dest="shadow_seed", type=int, default=0,
        help="shadow sampling seed (same seed = same shadow set)",
    )
    sv.add_argument(
        "--watch-snapshots", dest="watch_snapshots", metavar="PREFIX",
        help="training snapshot_prefix the hot-swap remediation "
        "watches for newer committed snapshots (the train→serve "
        "freshness loop's actuation half; pair with --snapshot for "
        "the initial model)",
    )
    sv.add_argument(
        "--explicit-drops", dest="explicit_drops", action="store_true",
        help="write queries_dropped into the drain summary and "
        "/healthz even at 0 (the gameday zero-drop posture: zero is "
        "evidence, not a default — docs/RESILIENCE.md §Gameday); off, "
        "the key appears only when nonzero",
    )
    sv.add_argument(
        "--qtrace", action="store_true",
        help="per-query tracing (docs/OBSERVABILITY.md §Query "
        "tracing): per-stage spans from admission to answer, always-on "
        "stage histograms + p99 budget decomposition, and the "
        "npairloss-qtrace-v1 exemplar artifact (qtrace.json in the "
        "telemetry dir; SLO-violating and slowest-tail queries keep "
        "full span trees) — needs --telemetry-dir; off (default) "
        "keeps every stream byte-identical",
    )
    sv.add_argument(
        "--qtrace-exemplars", dest="qtrace_exemplars", type=int,
        default=64, metavar="N",
        help="exemplar ring capacity — full span trees retained for "
        "the worst queries (default 64; evicts the fastest retained "
        "exemplar when full)",
    )
    sv.add_argument(
        "--qtrace-slo-ms", dest="qtrace_slo_ms", type=float,
        default=0.0, metavar="MS",
        help="per-query latency SLO for exemplar retention + the "
        "violations counter (default 0 = the armed serve_p99 "
        "watchdog's target when --live-obs is on, else 250)",
    )
    sv.add_argument(
        "--wal-dir", dest="wal_dir", metavar="DIR",
        help="durable-ingest write-ahead log directory "
        "(npairloss-wal-v1 — docs/RESILIENCE.md §Durability): every "
        "stdin ingest record is WAL-appended + fsynced BEFORE its ack, "
        "cold restart replays records above the newest index "
        "snapshot's watermark, and checkpoints publish under "
        "--index-prefix (required with this flag); off (default) "
        "rejects ingest records",
    )
    sv.add_argument(
        "--wal-flush-ms", dest="wal_flush_ms", type=float, default=0.0,
        metavar="MS",
        help="group-commit fsync interval: acks wait for the covering "
        "flush (amortizes fsyncs across concurrent ingests); 0 "
        "(default) fsyncs inline on every append",
    )
    sv.add_argument(
        "--wal-checkpoint-every", dest="wal_checkpoint_every",
        type=int, default=8, metavar="N",
        help="publish an ingest checkpoint (and GC covered WAL "
        "segments) every N acked ingest batches; a final checkpoint "
        "always lands at drain (default 8; 0 = drain-only)",
    )
    sv_tel = sv.add_mutually_exclusive_group()
    sv_tel.add_argument(
        "--telemetry-dir", dest="telemetry_dir", metavar="DIR",
        help="run-telemetry directory (manifest + per-window serve "
        "metric rows + span trace) — see docs/OBSERVABILITY.md",
    )
    sv_tel.add_argument(
        "--trace-dir", dest="trace_dir", metavar="DIR",
        help="span tracing only (serve/admit|batch|dispatch|topk)",
    )
    sv.set_defaults(fn=cmd_serve)

    tl = sub.add_parser(
        "timeline",
        help="merge a run directory's timeline sources (trainer rank "
        "traces, serve host spans, qtrace exemplar span trees, "
        "alert/remediation/chaos instants) into one Perfetto-loadable "
        "timeline.json — docs/OBSERVABILITY.md §Query tracing",
    )
    tl.add_argument("run_dir", metavar="RUNDIR",
                    help="run/telemetry directory (gameday out dirs "
                    "with serve_tel/ + train_tel/ work as-is)")
    tl.add_argument("--out", default=None, metavar="PATH",
                    help="output path (default: RUNDIR/timeline.json)")
    tl.set_defaults(fn=cmd_timeline)

    im = sub.add_parser(
        "import-caffemodel",
        help="migrate a trained .caffemodel trunk to a --weights file",
    )
    im.add_argument("--weights", required=True, help=".caffemodel path")
    im.add_argument(
        "--model", default="googlenet",
        help="target model (plain googlenet; train --weights converts "
        "to s2d/fused layouts automatically)",
    )
    im.add_argument("--out", default="./pretrained.msgpack")
    im.set_defaults(fn=cmd_import_caffemodel)

    exp = sub.add_parser(
        "export-caffemodel",
        help="write a trunk trained here back out as .caffemodel",
    )
    exp.add_argument(
        "--weights",
        help="params .msgpack (from import-caffemodel)",
    )
    exp.add_argument(
        "--snapshot",
        help="export straight from a training snapshot (.ckpt dir) "
        "instead of --weights",
    )
    exp.add_argument(
        "--model", default="googlenet",
        help="trunk family the weights belong to (googlenet | resnet50)",
    )
    exp.add_argument("--out", default="./model.caffemodel")
    exp.add_argument(
        "--solverstate-out", dest="solverstate_out", metavar="PATH",
        help="also write the optimizer state (momentum + iteration) as "
        "a Caffe .solverstate (GoogLeNet trunks; needs --snapshot)",
    )
    exp.set_defaults(fn=cmd_export_caffemodel)

    tm = sub.add_parser(
        "time",
        help="benchmark a net's forward/backward (the caffe time action)",
    )
    tm.add_argument(
        "--net", help="net prototxt to time (like caffe time -model)"
    )
    tm.add_argument(
        "--solver",
        help="optional solver prototxt (only its net path is used)",
    )
    tm.add_argument("--model", help="model registry name (default: from net)")
    tm.add_argument(
        "--iterations", type=int, default=10,
        help="scan length per timed stage (caffe time -iterations)",
    )
    tm_geom = tm.add_mutually_exclusive_group()
    tm_geom.add_argument(
        "--batch", type=int,
        help="override total batch size (rounded down to a multiple of "
        "the net's images/identity)",
    )
    tm_geom.add_argument(
        "--ids", type=int, help="override identities per batch",
    )
    tm.add_argument(
        "--forward-only", dest="forward_only", action="store_true",
        help="skip the forward+backward stage",
    )
    tm.add_argument("--mesh", type=int, help="devices in the dp mesh")
    tm.add_argument(
        "--engine", choices=["dense", "ring", "blockwise"],
        help="loss engine (see train --engine)",
    )
    tm.add_argument("--bf16", action="store_true", help="bfloat16 trunk")
    tm.add_argument(
        "--precision", choices=_PRECISION_CHOICES, default=None,
        help="mixed-precision policy (see train --precision)",
    )
    tm.add_argument(
        "--sim-cache", dest="sim_cache", choices=["auto", "on", "off"],
        default="auto", help="see train --sim-cache",
    )
    tm.add_argument(
        "--pos-topk", dest="pos_topk", type=_pos_topk_arg, default="auto",
        help="see train --pos-topk",
    )
    tm.add_argument(
        "--matmul-precision", dest="matmul_precision",
        choices=["highest", "default"],
        help="see train --matmul-precision",
    )
    tm.add_argument(
        "--remat", action="store_true",
        help="block-remat GoogLeNet trunks (see train --remat)",
    )
    tm.add_argument(
        "--caffe-pad", dest="caffe_pad", action="store_true",
        help="see train --caffe-pad",
    )
    tm.add_argument("--resume", help="snapshot to time (restored weights)")
    tm.set_defaults(fn=cmd_time)

    dq = sub.add_parser(
        "device-query",
        help="enumerate accelerators (the caffe device_query action)",
    )
    dq.set_defaults(fn=cmd_device_query)

    pr = sub.add_parser(
        "prof",
        help="perf observatory: per-region HLO cost attribution + "
        "roofline bound-class + step-time decomposition report "
        "(docs/OBSERVABILITY.md §Perf)",
    )
    pr.add_argument(
        "--step", choices=["train", "serve"], default="train",
        help="which jitted program to profile",
    )
    pr.add_argument(
        "--fleet", metavar="RUNDIR",
        help="offline fleet aggregation: read a fleet run directory's "
        "per-rank telemetry (telemetry.r<k>.jsonl + trace.r<k>.json), "
        "emit the npairloss-fleet-report-v1 straggler/skew/comms "
        "report and a merged Perfetto timeline (ignores the live-"
        "profiling flags; no backend touched)",
    )
    pr.add_argument(
        "--quality", metavar="RUNDIR",
        help="offline quality report: validate a serving run's "
        "npairloss-quality-v1 shadow-recall log (quality.jsonl) and "
        "render the recall trend vs the committed parity baseline "
        "(docs/OBSERVABILITY.md §Quality observatory; no backend "
        "touched)",
    )
    pr.add_argument("--model", default="googlenet",
                    help="model registry name (train)")
    pr.add_argument("--batch", type=int, default=8,
                    help="train batch size (identity pairs)")
    pr.add_argument("--image", type=int, default=224,
                    help="input side (or flat dim for --model mlp)")
    pr.add_argument("--steps", type=int, default=4,
                    help="measured steps/queries for the dynamic layer")
    pr.add_argument("--engine", choices=["dense", "ring", "blockwise"],
                    help="loss engine (train)")
    pr.add_argument("--mesh", type=int, default=0,
                    help="devices in the dp mesh (train; 0 = single)")
    pr.add_argument("--bf16", action="store_true",
                    help="bf16 trunk activations (train)")
    pr.add_argument("--precision", choices=_PRECISION_CHOICES,
                    default=None,
                    help="mixed-precision policy for the profiled trunk "
                    "(see train --precision); the before/after roofline "
                    "recipe is fp32_parity vs mxu")
    pr.add_argument("--gallery", type=int, default=2048,
                    help="synthetic gallery rows (serve)")
    pr.add_argument("--dim", type=int, default=64,
                    help="embedding dim (serve)")
    pr.add_argument("--top-k", dest="top_k", type=int, default=10)
    pr.add_argument("--buckets", default="1,8,32",
                    help="query padding buckets (serve)")
    pr.add_argument("--region-depth", dest="region_depth", type=int,
                    default=2,
                    help="named-scope path depth to aggregate regions at")
    pr.add_argument("--out", default=None,
                    help="report output directory (default: perf_reports "
                    "for live profiles, the run dir itself for --fleet)")
    pr.set_defaults(fn=cmd_prof)

    w = sub.add_parser(
        "watch",
        help="evaluate SLO watchdogs over a run directory's telemetry "
        "offline (the live observatory's second feed; no backend)",
    )
    w.add_argument("run_dir", metavar="RUNDIR",
                   help="run directory holding metrics.jsonl or "
                   "per-rank telemetry.r<k>.jsonl streams")
    w.add_argument(
        "--slo-config", dest="slo_config", metavar="PATH",
        help="SLO config (JSON/TOML); default: the --watchdogs presets",
    )
    w.add_argument(
        "--watchdogs", default="train,serve",
        help="comma-separated watchdog preset kinds when no --slo-config "
        "(default train,serve — a kind whose metrics never appear "
        "just stays ok)",
    )
    w.add_argument(
        "--follow", action="store_true",
        help="keep tailing the streams instead of one replay pass",
    )
    w.add_argument(
        "--poll-s", dest="poll_s", type=float, default=1.0,
        help="--follow poll period (default 1.0)",
    )
    w.add_argument(
        "--for", dest="for_s", type=float, default=None, metavar="S",
        help="stop --follow after S seconds (default: until interrupted)",
    )
    w.add_argument(
        "--out", metavar="PATH",
        help="alert JSONL output (default RUNDIR/alerts.watch.jsonl — "
        "never the in-process engine's alerts.jsonl)",
    )
    w.set_defaults(fn=cmd_watch)

    gd = sub.add_parser(
        "gameday",
        help="production gameday (docs/RESILIENCE.md §Gameday): "
        "deterministic traffic + scripted chaos over the composed "
        "trainer/server/watch group, verdict-gated "
        "(npairloss-gameday-v1)",
    )
    gd.add_argument("--out", required=True, metavar="DIR",
                    help="run directory for every artifact (answers, "
                    "telemetry, logs, gameday.json)")
    gd.add_argument("--seed", type=int, default=0,
                    help="traffic seed — same seed, same compressed "
                    "day, byte for byte (default 0)")
    gd.add_argument("--duration", type=float, default=75.0,
                    metavar="S",
                    help="traffic window in seconds (default 75)")
    gd.add_argument("--schedule", metavar="PATH",
                    help="chaos schedule JSON (default: the shipped "
                    "compressed-day schedule; day scenario only)")
    gd.add_argument("--replicas", type=int, default=2,
                    help="serving replicas (default 2; >= 2 so the "
                    "day scenario's replica-crash entry has a "
                    "survivor)")
    gd.add_argument("--scenario", choices=("day", "tenant_skew"),
                    default="day",
                    help="'day' = the full compressed-day chaos drill; "
                    "'tenant_skew' = the multi-tenant noisy-neighbor "
                    "drill (docs/SERVING.md §Multi-tenant): one tier, "
                    "three tenant galleries, a hot-tenant burst that "
                    "must quota-shed and page WITHOUT degrading the "
                    "other tenants (default %(default)s)")
    gd.set_defaults(fn=cmd_gameday)

    sc = sub.add_parser(
        "staticcheck",
        help="repo-wide invariant linter (docs/STATICCHECK.md) — "
        "jax-free, enforces the contracts the runtime gates can only "
        "catch after the fact",
    )
    _add_staticcheck_options(sc)
    sc.set_defaults(fn=cmd_staticcheck)

    pp = sub.add_parser("parse", help="parse + dump a prototxt file")
    pp.add_argument("file")
    pp.add_argument("--json", action="store_true")
    pp.set_defaults(fn=cmd_parse)

    b = sub.add_parser("bench", help="run the benchmark")
    b.set_defaults(fn=cmd_bench, bench_args=[])

    # Everything after the literal "bench" goes to bench.py verbatim
    # (argparse REMAINDER in a subparser cannot capture leading
    # optionals like --smoke).
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    bench_args = []
    if "bench" in argv:
        idx = argv.index("bench")
        bench_args = argv[idx + 1:]
        argv = argv[:idx + 1]

    args = p.parse_args(argv)
    if getattr(args, "fn", None) is cmd_bench:
        args.bench_args = bench_args
    if args.platform != "default":
        import jax

        jax.config.update("jax_platforms", args.platform)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
