"""Command-line driver — the ``caffe train --solver=...`` counterpart.

The reference is launched as ``caffe train --solver=usage/solver.prototxt``
(SURVEY.md §3.1) under mpirun.  Here the same entrypoint is

    python -m npairloss_tpu train --solver usage/solver.prototxt

which parses the solver + net prototxts through the config front-end,
builds the embedding model and identity-balanced data iterators, and runs
the Solver loop on whatever accelerator JAX sees — multi-chip via
``--mesh`` (all devices by default) with the negative pool all-gathered
across the mesh in-graph.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import Optional

log = logging.getLogger("npairloss_tpu.cli")


def _build_data(net_cfg, phase: str, input_shape, seed: int = 0,
                synthetic: bool = False):
    """Batches for a phase: the real MultibatchData pipeline from the
    net's source list file, or synthetic identity-balanced clusters when
    ``--synthetic`` was passed explicitly.

    A missing/unreadable source is a hard error unless --synthetic: a
    typo'd path must never silently "train" on random clusters.
    """
    d = net_cfg.data.get(phase)
    if d is None:
        return None, None
    if not synthetic:
        if not d.source:
            raise SystemExit(
                f"{phase} data layer has no `source` list file; pass "
                "--synthetic to train on synthetic identity clusters"
            )
        if not os.path.exists(d.source):
            raise SystemExit(
                f"{phase} data source {d.source!r} does not exist; fix the "
                "net prototxt or pass --synthetic for synthetic data"
            )
        from npairloss_tpu.data import multibatch_loader

        return multibatch_loader(d, net_cfg.transformer, seed=seed), d
    from npairloss_tpu.data import synthetic_identity_batches

    ids = d.identity_num_per_batch or max(2, (d.batch_size or 8) // 2)
    imgs = d.img_num_per_identity or 2
    return (
        synthetic_identity_batches(
            max(ids * 4, ids), ids, imgs, input_shape, seed=seed
        ),
        d,
    )


def cmd_train(args) -> int:
    # The MPI_COMM_WORLD replacement: must run before the first backend
    # query (exactly as MPI_Init precedes any communicator use).
    from npairloss_tpu.parallel import initialize_distributed

    initialize_distributed(
        args.coordinator, args.num_processes, args.process_id
    )

    import jax

    from npairloss_tpu.config import load_net, load_solver
    from npairloss_tpu.models import get_model
    from npairloss_tpu.parallel import data_parallel_mesh
    from npairloss_tpu.train import Solver

    solver_cfg, net_path = load_solver(args.solver)
    if args.net:
        net_path = args.net
    elif net_path and not os.path.isabs(net_path):
        # Caffe resolves the net path relative to the CWD; fall back to
        # solver-relative when that misses (the shipped solver points at
        # a machine-specific ./conf_same_veri/ path).
        if not os.path.exists(net_path):
            cand = os.path.join(os.path.dirname(args.solver), net_path)
            net_path = cand if os.path.exists(cand) else net_path
    if not net_path or not os.path.exists(net_path):
        log.error("net prototxt not found (tried %r); pass --net", net_path)
        return 2
    net_cfg = load_net(net_path)

    if args.max_iter is not None:
        import dataclasses

        solver_cfg = dataclasses.replace(solver_cfg, max_iter=args.max_iter)
    if args.snapshot_prefix:
        import dataclasses

        solver_cfg = dataclasses.replace(
            solver_cfg, snapshot_prefix=args.snapshot_prefix
        )

    crop = 0
    train_data = net_cfg.data.get("TRAIN")
    if train_data is not None:
        crop = train_data.transform.crop_size
    side = crop or 224
    input_shape = (side, side, 3)

    loss_cfg = net_cfg.loss.loss if net_cfg.loss else None
    if loss_cfg is None:
        from npairloss_tpu.ops.npair_loss import NPairLossConfig

        loss_cfg = NPairLossConfig()

    mesh = None
    n_dev = len(jax.devices())
    want = args.mesh if args.mesh is not None else (n_dev if n_dev > 1 else 1)
    if want > 1:
        mesh = data_parallel_mesh(jax.devices()[:want])

    model_name = args.model or _model_for_net(net_cfg)
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    model = get_model(model_name, dtype=dtype)

    solver = Solver(
        model, loss_cfg, solver_cfg, mesh=mesh, input_shape=input_shape
    )
    if args.resume:
        solver.restore_snapshot(args.resume)

    train_iter, _ = _build_data(
        net_cfg, "TRAIN", input_shape, seed=0, synthetic=args.synthetic
    )
    test_iter, _ = _build_data(
        net_cfg, "TEST", input_shape, seed=1, synthetic=args.synthetic
    )
    if train_iter is None:
        log.error("net %s has no TRAIN MultibatchData layer", net_path)
        return 2

    logging.basicConfig(level=logging.INFO, format="%(message)s")
    final = solver.train(
        train_iter,
        num_iters=args.max_iter,
        test_batches=test_iter,
        log_fn=lambda s: print(s, flush=True),
    )
    print(json.dumps({k: float(v) for k, v in final.items()}))
    return 0


def _model_for_net(net_cfg) -> str:
    name = (net_cfg.name or "").lower().replace(" ", "")
    if "resnet" in name:
        return "resnet50"
    if "vit" in name:
        return "vit_b16"
    if "mlp" in name:
        return "mlp"
    return "googlenet"  # the reference's flagship trunk (def.prototxt:1)


def cmd_parse(args) -> int:
    from npairloss_tpu.config import dumps, parse_file

    msg = parse_file(args.file)
    if args.json:
        print(json.dumps(msg.to_dict(), indent=2, default=str))
    else:
        print(dumps(msg))
    return 0


def cmd_bench(args) -> int:
    import importlib.util

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(repo_root, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench.main()
    return 0


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="npairloss_tpu", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("train", help="train from a solver prototxt")
    t.add_argument("--solver", required=True)
    t.add_argument("--net", help="override the solver's net path")
    t.add_argument("--model", help="model registry name (default: from net)")
    t.add_argument("--max_iter", type=int, help="override solver max_iter")
    t.add_argument("--mesh", type=int, help="devices in the dp mesh")
    t.add_argument("--bf16", action="store_true", help="bfloat16 trunk")
    t.add_argument("--resume", help="snapshot path to restore")
    t.add_argument("--snapshot_prefix", help="override snapshot prefix")
    t.add_argument(
        "--synthetic", action="store_true",
        help="train on synthetic identity-balanced clusters instead of the "
        "net's data source (required opt-in; a missing source is an error)",
    )
    t.add_argument(
        "--coordinator",
        help="multi-process coordinator HOST:PORT (the mpirun counterpart); "
        "omit on TPU pods for autodetect",
    )
    t.add_argument("--num-processes", type=int, help="total host processes")
    t.add_argument("--process-id", type=int, help="this process's rank")
    t.set_defaults(fn=cmd_train)

    pp = sub.add_parser("parse", help="parse + dump a prototxt file")
    pp.add_argument("file")
    pp.add_argument("--json", action="store_true")
    pp.set_defaults(fn=cmd_parse)

    b = sub.add_parser("bench", help="run the benchmark")
    b.set_defaults(fn=cmd_bench)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
