"""NumPy oracle for the N-pair loss — the golden-test authority.

A deliberately literal, loop-level NumPy rendering of the reference layer's
semantics (npair_multi_class_loss.cu:207-499), simulating G MPI ranks in one
process: rank r holds batch block r; MPI_Allgather is a concatenation;
MPI_Allreduce(SUM) is a sum over ranks.  Slow and simple on purpose — the
JAX implementation is tested against this, not the other way round.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import numpy as np

from npairloss_tpu.ops.npair_loss import MiningMethod, MiningRegion, NPairLossConfig

FLT_MAX = float(np.finfo(np.float32).max)


def _relative_pos(size: int, sn: float) -> int:
    # cu:285-287 etc.: C truncation toward zero in both branches.
    if sn >= 0:
        pos = size - 1 - int(sn)
    else:
        pos = int(size - 1 + sn * size)
    return min(max(pos, 0), max(size - 1, 0))  # reference is UB out of range


def _lookup(sorted_list: List[float], sn: float) -> float:
    if not sorted_list:
        return FLT_MAX  # matches the JAX fill for an empty list
    val = sorted_list[_relative_pos(len(sorted_list), sn)]
    return val if val >= 0 else -FLT_MAX  # cu:288 quirk


@dataclasses.dataclass
class RankResult:
    loss: float
    recalls: Dict[int, float]
    feature_asum: float
    sims: np.ndarray
    sim_exp: np.ndarray
    same: np.ndarray
    diff: np.ndarray
    select: np.ndarray
    pos_thr: np.ndarray
    neg_thr: np.ndarray
    max_all: np.ndarray
    exp_pos: np.ndarray
    exp_neg: np.ndarray
    ident_sum: np.ndarray
    all_sum: np.ndarray
    grad: np.ndarray | None = None


def forward(
    features: Sequence[np.ndarray],
    labels: Sequence[np.ndarray],
    cfg: NPairLossConfig,
    top_ks: Sequence[int] = (1, 5, 10),
    dtype=np.float32,
) -> List[RankResult]:
    """Run the forward pass for every simulated rank.

    ``dtype`` is the reference's ``Dtype`` template parameter
    (npair_multi_class_loss.cu:38-41 dispatches MPI_FLOAT/MPI_DOUBLE by
    ``sizeof(Dtype)``): ``np.float64`` renders the double instantiation.
    The mining clamps stay FLT_MAX in BOTH precisions — the reference
    writes ``(Dtype)-FLT_MAX`` (cu:230-236, cu:288), not DBL_MAX.
    """
    g = len(features)
    total_f = np.concatenate([f.astype(dtype) for f in features], axis=0)
    total_l = np.concatenate([l.astype(dtype) for l in labels], axis=0)
    out = []
    for rank in range(g):
        out.append(
            _forward_rank(
                features[rank].astype(dtype),
                labels[rank].astype(dtype),
                total_f,
                total_l,
                rank,
                cfg,
                top_ks,
                dtype,
            )
        )
    return out


def _forward_rank(f, l, total_f, total_l, rank, cfg, top_ks,
                  dtype=np.float32):
    n, d = f.shape
    ng = total_f.shape[0]
    sims = (f @ total_f.T).astype(dtype)

    # Masks (GetLabelDiffMtx, cu:44-66): self pair excluded from both.
    same = np.zeros((n, ng), dtype=bool)
    diff = np.zeros((n, ng), dtype=bool)
    for q in range(n):
        for b in range(ng):
            if q + rank * n == b:
                continue
            if l[q] == total_l[b]:
                same[q, b] = True
            else:
                diff[q, b] = True

    # Mining statistics (cu:222-273).  FLT_MAX fills in both precisions
    # — the reference caffe_sets (Dtype)-FLT_MAX (cu:230-236).
    max_all = np.full(n, -FLT_MAX, dtype=dtype)
    min_within = np.full(n, FLT_MAX, dtype=dtype)
    max_between = np.full(n, -FLT_MAX, dtype=dtype)
    ident_global: List[float] = []
    diff_global: List[float] = []
    ident_local: List[List[float]] = []
    diff_local: List[List[float]] = []
    for q in range(n):
        iq: List[float] = []
        dq: List[float] = []
        for b in range(ng):
            s = sims[q, b]
            if same[q, b]:
                min_within[q] = min(min_within[q], s)
                max_all[q] = max(max_all[q], s)
                iq.append(s)
                ident_global.append(s)
            elif diff[q, b]:
                max_between[q] = max(max_between[q], s)
                max_all[q] = max(max_all[q], s)
                dq.append(s)
                diff_global.append(s)
        ident_local.append(sorted(iq))
        diff_local.append(sorted(dq))
    ident_global.sort()
    diff_global.sort()

    # Threshold selection (cu:275-337).
    relative = (MiningMethod.RELATIVE_HARD, MiningMethod.RELATIVE_EASY)
    pos_thr = np.zeros(n, dtype=dtype)
    neg_thr = np.zeros(n, dtype=dtype)
    if cfg.ap_mining_region == MiningRegion.LOCAL:
        if cfg.ap_mining_method in relative:
            for q in range(n):
                pos_thr[q] = _lookup(ident_local[q], cfg.identsn)
        else:
            pos_thr[:] = max_between
    else:
        if cfg.ap_mining_method in relative:
            pos_thr[:] = _lookup(ident_global, cfg.identsn)
        else:
            pos_thr[:] = diff_global[-1] if diff_global else -FLT_MAX
    if cfg.an_mining_region == MiningRegion.LOCAL:
        if cfg.an_mining_method in relative:
            for q in range(n):
                neg_thr[q] = _lookup(diff_local[q], cfg.diffsn)
        else:
            neg_thr[:] = min_within
    else:
        if cfg.an_mining_method in relative:
            neg_thr[:] = _lookup(diff_global, cfg.diffsn)
        else:
            neg_thr[:] = ident_global[0] if ident_global else FLT_MAX

    # Selection (GetSampledPairMtx, cu:69-122).
    select = np.zeros((n, ng), dtype=bool)
    for q in range(n):
        pt = pos_thr[q] + dtype(cfg.margin_ident)
        nt = neg_thr[q] + dtype(cfg.margin_diff)
        for b in range(ng):
            s = sims[q, b]
            if same[q, b]:
                m = cfg.ap_mining_method
                select[q, b] = (
                    (m == MiningMethod.HARD and s < pt)
                    or (m == MiningMethod.EASY and s >= pt)
                    or m == MiningMethod.RAND
                    or (m == MiningMethod.RELATIVE_HARD and s <= pt)
                    or (m == MiningMethod.RELATIVE_EASY and s >= pt)
                )
            elif diff[q, b]:
                m = cfg.an_mining_method
                select[q, b] = (
                    (m == MiningMethod.HARD and s > nt)
                    or (m == MiningMethod.EASY and s <= nt)
                    or m == MiningMethod.RAND
                    or (m == MiningMethod.RELATIVE_HARD and s >= nt)
                    or (m == MiningMethod.RELATIVE_EASY and s <= nt)
                )
    sel_pos = (same & select).astype(dtype)
    sel_neg = (diff & select).astype(dtype)

    # Stabilized loss (cu:124-171, cu:362-388).
    sim_exp = np.exp(sims - max_all[:, None]).astype(dtype)
    exp_pos = sim_exp * sel_pos
    exp_neg = sim_exp * sel_neg
    ident_sum = exp_pos.sum(axis=1)
    all_sum = ident_sum + exp_neg.sum(axis=1)
    loss = 0.0
    for q in range(n):
        if ident_sum[q] != 0 and all_sum[q] != 0:
            loss += np.log(ident_sum[q] / all_sum[q])
    loss = -loss / n

    # Retrieval metric (GetRetrivePerformance, cu:173-206) on the exp'd matrix.
    recalls = {}
    for k in top_ks:
        hits = 0
        for q in range(n):
            vals = [sim_exp[q, b] for b in range(ng) if b != rank * n + q]
            vals.sort(reverse=True)
            thr = vals[min(k, len(vals) - 1)]
            for b in range(ng):
                if b == rank * n + q:
                    continue
                if sim_exp[q, b] > thr and l[q] == total_l[b]:
                    hits += 1
                    break
        recalls[k] = hits / n

    asum = float(np.abs(f).sum() / n)
    return RankResult(
        loss=float(loss),
        recalls=recalls,
        feature_asum=asum,
        sims=sims,
        sim_exp=sim_exp,
        same=same,
        diff=diff,
        select=select,
        pos_thr=pos_thr,
        neg_thr=neg_thr,
        max_all=max_all,
        exp_pos=exp_pos,
        exp_neg=exp_neg,
        ident_sum=ident_sum,
        all_sum=all_sum,
    )


def backward(
    features: Sequence[np.ndarray],
    results: Sequence[RankResult],
    loss_weight: float = 1.0,
    dtype=np.float32,
) -> List[np.ndarray]:
    """Per-rank feature gradients with the reference's exact scaling.

    (Backward_gpu, cu:420-499: dot_normalizer = N; MPI_Allreduce(SUM) of the
    database-role gradient then 1/G; final 0.5/0.5 role averaging.
    ``dtype`` as in :func:`forward` — np.float64 for the double path.)
    """
    g_ranks = len(features)
    n = features[0].shape[0]
    total_f = np.concatenate([f.astype(dtype) for f in features], axis=0)

    db_grads = []
    query_grads = []
    for res in results:
        p1 = np.where(
            res.ident_sum[:, None] != 0, res.exp_pos / np.where(res.ident_sum[:, None] != 0, res.ident_sum[:, None], 1.0), 0.0
        )
        p2 = np.where(
            res.all_sum[:, None] != 0, res.exp_pos / np.where(res.all_sum[:, None] != 0, res.all_sum[:, None], 1.0), 0.0
        )
        p3 = np.where(
            res.all_sum[:, None] != 0, res.exp_neg / np.where(res.all_sum[:, None] != 0, res.all_sum[:, None], 1.0), 0.0
        )
        w = (-p1 + p2 + p3) * (loss_weight / n)
        query_grads.append(w @ total_f)
        db_grads.append(w.T)  # multiplied with local features below

    # Allreduce(SUM) of database-role grads then scale 1/G (cu:462-489).
    db_total = np.zeros_like(total_f)
    for rank in range(g_ranks):
        db_total += db_grads[rank] @ features[rank].astype(dtype)
    db_total /= g_ranks

    out = []
    for rank in range(g_ranks):
        local = db_total[rank * n : (rank + 1) * n]
        final = 0.5 * local + 0.5 * query_grads[rank]  # cu:492-497
        out.append(final.astype(dtype))
        results[rank].grad = out[-1]
    return out
