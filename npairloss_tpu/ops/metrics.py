"""In-training retrieval metrics, on-device.

The reference computes Recall@k with a per-query host-side std::sort over the
exp'd similarity row (GetRetrivePerformance, npair_multi_class_loss.cu:173-206)
and a feature-magnitude monitor (cu:400-401).  Here both are fixed-shape
``lax.top_k``/reductions inside the jitted graph — no host sync.

Reference semantics preserved exactly:
  * the self column (gathered index rank*N + q) is excluded (cu:182, cu:196);
  * the threshold is the sorted-descending value at index
    ``min(top_k, list_size - 1)`` over the N*G - 1 non-self sims (cu:190);
  * a query counts as retrieved iff some non-self item has sim STRICTLY
    greater than the threshold AND the same label (cu:197) — ties at the
    threshold do not count;
  * the metric operates on the exp'd matrix (rank-preserving per row, cu:132).
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_NEG_FILL = float(-np.finfo(np.float32).max)

# k-list the reference wires up (cu:390-394); with the canonical 5-top layout
# only {1, 5, 10} are consumed (k=15 defined but unused, SURVEY.md C16).
TOP_K_LIST = (1, 5, 10, 15)


def recall_at_k(
    sim_exp: jax.Array,
    local_labels: jax.Array,
    total_labels: jax.Array,
    rank: jax.Array,
    top_k: int,
) -> jax.Array:
    """Fraction of queries with a same-label item above the top-k threshold."""
    n_local, n_total = sim_exp.shape
    col = jnp.arange(n_total, dtype=jnp.int32)[None, :]
    row_global = jnp.arange(n_local, dtype=jnp.int32)[:, None] + rank * n_local
    not_self = col != row_global

    masked = jnp.where(not_self, sim_exp, jnp.float32(_NEG_FILL))
    # Non-self list size is n_total - 1; threshold index min(top_k, size - 1).
    thr_idx = min(top_k, n_total - 2)
    top_vals, _ = jax.lax.top_k(masked, thr_idx + 1)
    threshold = top_vals[:, thr_idx]

    same_lbl = local_labels[:, None] == total_labels[None, :]
    hit = jnp.any((masked > threshold[:, None]) & same_lbl & not_self, axis=1)
    return hit.sum().astype(jnp.float32) / jnp.float32(n_local)


def feature_asum(features: jax.Array) -> jax.Array:
    """Mean absolute feature sum: asum(features)/N (cu:400-401).

    After L2 normalization this sits near a constant — it is the reference's
    sanity monitor for the normalize layer (SURVEY.md §5.5).
    """
    n = features.shape[0]
    return jnp.abs(features.astype(jnp.float32)).sum() / jnp.float32(n)


def embedding_magnitude(features: jax.Array) -> Dict[str, jax.Array]:
    """Row-L2-norm mean/max — the feature monitor generalized.

    ``feature_asum`` reproduces the reference's exact asum probe
    (cu:400-401); this is the version worth alarming on: after the
    L2Normalize layer every row norm is 1.0 by construction, so
    ``emb_mag_mean`` drifting from 1 (or ``emb_mag_max`` spiking) means
    the normalize layer or its gradient broke.  Consumed by
    ``obs.health`` as an optional in-graph health signal.
    """
    norms = jnp.linalg.norm(features.astype(jnp.float32), axis=-1)
    return {
        "emb_mag_mean": norms.mean(),
        "emb_mag_max": norms.max(),
    }


def retrieval_metrics(
    aux: Dict[str, jax.Array],
    local_labels: jax.Array,
    features: jax.Array,
    top_ks: Sequence[int] = (1, 5, 10),
) -> Dict[str, jax.Array]:
    """The reference's metric tops: Recall@k per ``top_ks`` + feature_asum.

    ``aux`` is the second output of ``npair_loss_with_aux``.  Names mirror the
    def.prototxt top naming (retrieve_top1/5/10, feature_asum).
    """
    out = {}
    for k in top_ks:
        out[f"retrieve_top{k}"] = recall_at_k(
            aux["sim_exp"], local_labels, aux["total_labels"], aux["rank"], k
        )
    out["feature_asum"] = feature_asum(features)
    return out
