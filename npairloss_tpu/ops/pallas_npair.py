"""Fused blockwise N-pair loss as Pallas TPU kernels.

The dense path (``ops.npair_loss``) materializes the full N x M pair
matrix (M = pool size) in HBM — the TPU transplant of the reference's
``_innerProd`` workspace blob (reference: npair_multi_class_loss.cu:218,
cpp:55-64).  At the 32k-batch stretch config that matrix is gigabytes,
and HBM bandwidth (not MXU FLOPs) dominates: the matrix is written once
and re-read by every stage (stats, selection, exp, reductions).

These kernels never materialize it.  Queries and pool both stream
through VMEM in (BN x BM) tiles over a 2-D grid; each tile is produced
on the MXU and consumed in-register by the fused mask ->
threshold-compare -> exp -> row-sum pipeline — the flash-attention trick
transplanted to contrastive similarity (SURVEY.md §5.7), as explicit
Pallas kernels for fusion control the XLA autofuser cannot guarantee
across a gemm:

  * ``_stats_kernel``  — running per-query min-within / max-between /
    max-all (the mining statistics of cu:229-265; the reference runs
    this O(N*M) scan on the *host*, one float at a time).
  * ``_loss_kernel``   — selection mask from absolute thresholds
    (cu:69-122), stabilized exp (cu:124-156), running I_q/D_q sums and
    pair counts (cu:355-378).
  * ``_gq_kernel`` / ``_gdb_kernel`` — recompute the weight tile
    w = (-p1+p2+p3) * g/N (Get_Query_Diff_Part, cu:405-419) and
    accumulate the two gemms of cu:448-460: query-role grad w @ pool
    (pool axis innermost) and database-role grad w^T @ feats (query
    axis innermost), so each output block stays VMEM-resident across
    its whole accumulation.

Mining-method support matches the ring path (``parallel.ring``): ALL
methods are exact.  Absolute (HARD / EASY / RAND) thresholds stream as
min/max reductions inside ``_stats_kernel``; RELATIVE_* thresholds —
rank statistics over the full pair population, which the reference
obtains by sorting the whole matrix on the host (cu:266-273) — are
recovered exactly by MSD radix selection (``ops.rank_select``): a few
extra streamed passes over the pair tiles, each histogramming one
RADIX_BITS-bit digit of the monotone sortable float key via
scatter-free compare-and-reduce, narrow the target rank to a single
bit pattern without ever materializing the population.  When only the
POSITIVE side is relative (the flagship def.prototxt config), the
sparse-positive fast path (``pos_topk``) skips those passes entirely:
identity-balanced sampling gives each query only a handful of
positives, so the stats sweep keeps a K-slot buffer of the largest
same-label sims (``_accum_topk``) and the AP threshold is an N x K
sort — the flagship config then costs the same sweeps as absolute
mining, with a runtime ``lax.cond`` fallback to radix selection for
labels that overflow the buffer.

**Similarity cache**: every sweep above recomputes its sim tiles with a
fp32-HIGHEST MXU matmul (6 bf16 passes) plus a full stream of the feats
and pool tiles.  When the fp32 tile matrix fits HBM (``sim_cache``,
auto-enabled below ``SIM_CACHE_AUTO_BYTES``), the stats sweep writes
each tile out once and every later sweep — radix digits, loss, both
backward gemms — streams the cached tiles back instead, turning the
selection/loss sweeps from matmul-bound into purely bandwidth-bound
(at a 32k pool: ~4.3 GB read per sweep instead of a ~1.1e12-FLOP
fp32-HIGHEST matmul plus ~8.6 GB of operand re-streaming).  Cached and
recompute paths are bit-identical — the cache stores exactly the fp32
values ``_sim_tile`` produces.  Beyond the threshold the engine keeps
the original O(N x block) recompute behavior, which is the mode the
"too big to materialize" docstring above describes.

On non-TPU backends the kernels run in Pallas interpreter mode, which is
how the CPU test suite checks bit-parity against the dense path.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from npairloss_tpu.ops.npair_loss import (
    FLT_MAX,
    SIM_CACHE_AUTO_BYTES,
    resolve_sim_cache_auto,
    MiningMethod,
    MiningRegion,
    NPairLossConfig,
    _clamp_negative,
    _relative_pos,
    absolute_thresholds,
    active_matmul_precision,
    matmul_precision_ctx,
    selection_predicates,
    topk_relative_threshold,
)
from npairloss_tpu.ops.rank_select import (
    NUM_DIGITS,
    RADIX_BINS,
    digit_of,
    population_count_dtype,
    prefix_matches,
    radix_begin,
    radix_finish,
    radix_update,
    sortable_key,
)

_RELATIVE = (MiningMethod.RELATIVE_HARD, MiningMethod.RELATIVE_EASY)


def blockwise_supported(cfg: NPairLossConfig) -> bool:
    """Every mining configuration streams (RELATIVE_* via radix select),
    matching the ring path's support matrix."""
    return True


def _default_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _canon_labels(labels: jax.Array) -> jax.Array:
    """Kernel-friendly labels WITHOUT collapsing identities: float labels
    stay float32 (the dense path compares raw labels — int32 truncation
    would merge e.g. 0.2 and 0.7), ints become int32."""
    if jnp.issubdtype(labels.dtype, jnp.floating):
        return labels.astype(jnp.float32)
    return labels.astype(jnp.int32)


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


def _pad_rows(x: jax.Array, block: int) -> jax.Array:
    n = x.shape[0]
    np_ = ((n + block - 1) // block) * block
    if np_ == n:
        return x
    pad = [(0, np_ - n)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad)


def _row(x):
    """Per-query/per-pool scalar vectors travel as (1, N): the lane axis
    carries the index, so TPU (8,128) tiling stores them compactly — a
    (N, 1) layout would lane-pad every query to 128 floats and blow VMEM
    at large N."""
    return x.reshape(1, -1)


def _tile_masks(scal_ref, labels_ref, pool_labels_ref, qi, ii, bn: int, bm: int):
    """(same, diff) bool masks for tile (qi, ii) of the N x M pair grid.

    Self-pair exclusion (cu:54): global pool column ``self_offset + row``
    is this query's own embedding.  Padded rows (>= n_real) and padded
    columns (>= m_real) are in neither mask, so every downstream
    reduction and weight tile ignores them.
    """
    m_real = scal_ref[0]
    self_offset = scal_ref[1]
    n_real = scal_ref[2]
    col = jax.lax.broadcasted_iota(jnp.int32, (bn, bm), 1) + ii * bm
    row = jax.lax.broadcasted_iota(jnp.int32, (bn, bm), 0) + qi * bn
    valid = (col < m_real) & (row < n_real)
    not_self = col != (row + self_offset)
    same_lbl = labels_ref[:].T == pool_labels_ref[:]
    same = same_lbl & valid & not_self
    diff = (~same_lbl) & valid & not_self
    return same, diff


# Every kernel gemm reads the trace-time precision ContextVar
# (ops.npair_loss.active_matmul_precision): HIGHEST by default — the
# TPU default mode would truncate to bf16 and break bit-parity with
# the dense path (cu:218 semantics) — and the single-pass bf16 mode
# when ``blockwise_npair_loss(matmul_precision="default")`` wraps the
# trace in ``matmul_precision_ctx``.  Kernels are rebuilt at every
# trace, so the setting is captured per-computation and thread-safely.
_precision_ctx = matmul_precision_ctx


def _sim_tile(feats_ref, pool_ref):
    return jnp.dot(
        feats_ref[:],
        pool_ref[:].T,
        preferred_element_type=jnp.float32,
        precision=active_matmul_precision(),
    )


def _sim_kernel(body, extra: Optional[str] = None):
    """Build the cached/uncached kernel signatures around a sim-consuming
    ``body(scal_ref, labels_ref, pool_labels_ref, sims, extra_ref, rest)``.

    The uncached kernel streams feats+pool and recomputes the sim tile on
    the MXU; the cached kernel streams the sim tile itself plus — when
    ``extra`` is "feats"/"pool" — the one dense operand the body still
    multiplies against (the backward gemms).  Returns ``make(cached)``.
    """

    def make(cached: bool):
        if cached and extra is None:
            def kernel(scal_ref, labels_ref, pool_labels_ref, sims_ref,
                       *rest):
                body(scal_ref, labels_ref, pool_labels_ref, sims_ref[:],
                     None, rest)
        elif cached:
            def kernel(scal_ref, labels_ref, pool_labels_ref, sims_ref,
                       extra_ref, *rest):
                body(scal_ref, labels_ref, pool_labels_ref, sims_ref[:],
                     extra_ref, rest)
        else:
            def kernel(scal_ref, feats_ref, labels_ref, pool_ref,
                       pool_labels_ref, *rest):
                extra_ref = {"feats": feats_ref, "pool": pool_ref,
                             None: None}[extra]
                body(scal_ref, labels_ref, pool_labels_ref,
                     _sim_tile(feats_ref, pool_ref), extra_ref, rest)
        return kernel

    return make


def _selection(sims, same, diff, pt, nt, cfg: NPairLossConfig):
    """Tile selection via the shared quirk-exact predicates of cu:80-119
    (ops.npair_loss.selection_predicates); cfg is static, so the
    branches resolve at trace time."""
    pos_sel, neg_sel = selection_predicates(sims, pt, nt, cfg)
    return same & pos_sel, diff & neg_sel


# ---------------------------------------------------------------------------
# Kernels.  Grid convention: the output-resident axis is OUTER, the
# accumulation axis is INNER, so each output block is initialized once
# (inner index == 0) and accumulates in VMEM across the inner loop.
# ---------------------------------------------------------------------------


def _accum_digit_hist(out_ref, sims, mask, digit: int, prefix=None):
    """Accumulate the (RADIX_BINS, bn) histogram of one radix digit over
    a masked tile into ``out_ref`` — kernel-side compare-and-reduce (no
    scatter): one lane-reduction per bin, each written to its own
    static output row (row-wise ref updates keep the Mosaic op surface
    to the same relayouts the stats kernel already uses).  ``prefix``
    (optional, (bn, 1) uint32) restricts to entries whose higher digits
    match."""
    key = sortable_key(sims)
    m = mask
    if prefix is not None:
        m = m & prefix_matches(key, prefix, digit)
    d = jnp.where(m, digit_of(key, digit), RADIX_BINS)
    for b in range(RADIX_BINS):
        out_ref[b:b + 1, :] += (
            (d == b).sum(axis=1, keepdims=True).astype(jnp.int32).T
        )


def _accum_topk(out_ref, sims, mask, k: int):
    """Maintain the K largest masked sims per query across pool tiles.

    ``out_ref`` is a (K, bn) revisited output holding the running
    K-largest buffer (queries on lanes, slots on sublanes).  Per tile:
    K rounds of (row-max, remove exactly one occurrence) extract the
    tile's K largest — duplicate values are distinct candidates, so
    removal is by max-index-among-equals, never by value — then the
    same loop over the (2K, bn) concat merges tile and buffer.  Values
    come from the SAME ``sims`` the sweep computes, so thresholds built
    from the buffer are bit-identical to streamed radix selection.
    Cost: ~4K VPU passes per tile, beside a 2*D-MAC matmul."""
    bn, bm = sims.shape
    neg = jnp.float32(-FLT_MAX)
    vals = jnp.where(mask, sims, neg)
    iota = jax.lax.broadcasted_iota(jnp.int32, (bn, bm), 1)
    rows = []
    for _ in range(k):
        mx = vals.max(axis=1, keepdims=True)  # (bn, 1)
        mi = jnp.where(vals == mx, iota, -1).max(axis=1, keepdims=True)
        vals = jnp.where(iota == mi, neg, vals)
        rows.append(mx.T)
    work = jnp.concatenate([out_ref[:]] + rows, axis=0)  # (2K, bn)
    iota2 = jax.lax.broadcasted_iota(jnp.int32, (2 * k, bn), 0)
    for t in range(k):
        mx = work.max(axis=0, keepdims=True)  # (1, bn)
        mi = jnp.where(work == mx, iota2, -1).max(axis=0, keepdims=True)
        work = jnp.where(iota2 == mi, neg, work)
        out_ref[t:t + 1, :] = mx


def _make_stats_kernel(hist_same: bool, hist_diff: bool,
                       emit_sims: bool = False, topk_same: int = 0):
    """Mining-stats kernel; optionally also the digit-0 radix histograms
    for RELATIVE_* sides (digit 0 needs no prefix, so accumulating it in
    this sweep saves one whole pass per relative side), and optionally
    the fp32 sim tiles themselves (the similarity cache later sweeps
    stream instead of recomputing)."""

    def kernel(scal_ref, feats_ref, labels_ref, pool_ref, pool_labels_ref,
               *out_refs):
        (min_w_ref, max_b_ref, max_a_ref, cnt_s_ref, cnt_d_ref), rest = (
            out_refs[:5], list(out_refs[5:]))
        h_s_ref = rest.pop(0) if hist_same else None
        h_d_ref = rest.pop(0) if hist_diff else None
        topk_ref = rest.pop(0) if topk_same else None
        sims_out_ref = rest.pop(0) if emit_sims else None
        # grid = (num_q_blocks, num_pool_blocks)
        qi, ii = pl.program_id(0), pl.program_id(1)
        bn, bm = feats_ref.shape[0], pool_ref.shape[0]
        neg = jnp.float32(-FLT_MAX)
        pos = jnp.float32(FLT_MAX)

        @pl.when(ii == 0)
        def _():
            min_w_ref[:] = jnp.full_like(min_w_ref, pos)
            max_b_ref[:] = jnp.full_like(max_b_ref, neg)
            max_a_ref[:] = jnp.full_like(max_a_ref, neg)
            cnt_s_ref[:] = jnp.zeros_like(cnt_s_ref)
            cnt_d_ref[:] = jnp.zeros_like(cnt_d_ref)
            if h_s_ref is not None:
                h_s_ref[:] = jnp.zeros_like(h_s_ref)
            if h_d_ref is not None:
                h_d_ref[:] = jnp.zeros_like(h_d_ref)
            if topk_ref is not None:
                topk_ref[:] = jnp.full_like(topk_ref, neg)

        sims = _sim_tile(feats_ref, pool_ref)
        if sims_out_ref is not None:
            sims_out_ref[:] = sims
        same, diff = _tile_masks(
            scal_ref, labels_ref, pool_labels_ref, qi, ii, bn, bm
        )
        min_w_ref[:] = jnp.minimum(
            min_w_ref[:],
            jnp.where(same, sims, pos).min(axis=1, keepdims=True).T,
        )
        max_b_ref[:] = jnp.maximum(
            max_b_ref[:],
            jnp.where(diff, sims, neg).max(axis=1, keepdims=True).T,
        )
        max_a_ref[:] = jnp.maximum(
            max_a_ref[:],
            jnp.where(same | diff, sims, neg).max(axis=1, keepdims=True).T,
        )
        # Pair-population sizes (the ragged list sizes of cu:266-273)
        # feed the RELATIVE_* rank targets.
        cnt_s_ref[:] += same.sum(axis=1, keepdims=True).astype(jnp.int32).T
        cnt_d_ref[:] += diff.sum(axis=1, keepdims=True).astype(jnp.int32).T
        if h_s_ref is not None:
            _accum_digit_hist(h_s_ref, sims, same, 0)
        if h_d_ref is not None:
            _accum_digit_hist(h_d_ref, sims, diff, 0)
        if topk_ref is not None:
            _accum_topk(topk_ref, sims, same, topk_same)

    return kernel


def _make_hist_kernel(sides, digit: int, cached: bool = False):
    """Radix digit-histogram kernel for digits >= 1: one fused sweep
    produces the sim tile — MXU recompute, or a streamed read of the
    similarity cache when ``cached`` — and accumulates the prefix-matched
    digit histogram for every active RELATIVE side (the streamed
    counterpart of the reference's host std::sort, cu:266-273).

    ``sides``: tuple of bools — use_same per side, in output order.
    Inputs after the data refs: one (1, bn) uint32 prefix vector per
    side; outputs: one (RADIX_BINS, bn) int32 histogram per side.
    """

    def body(scal_ref, labels_ref, pool_labels_ref, sims, _extra, rest):
        prefix_refs = rest[:len(sides)]
        out_refs = rest[len(sides):]
        qi, ii = pl.program_id(0), pl.program_id(1)
        bn, bm = sims.shape

        @pl.when(ii == 0)
        def _():
            for o in out_refs:
                o[:] = jnp.zeros_like(o)

        same, diff = _tile_masks(
            scal_ref, labels_ref, pool_labels_ref, qi, ii, bn, bm
        )
        for use_same, p_ref, o_ref in zip(sides, prefix_refs, out_refs):
            mask = same if use_same else diff
            _accum_digit_hist(o_ref, sims, mask, digit, p_ref[:].T)

    return _sim_kernel(body)(cached)


def _make_loss_kernel(cfg: NPairLossConfig, cached: bool = False):
    def body(scal_ref, labels_ref, pool_labels_ref, sims, _extra, rest):
        (pos_thr_ref, neg_thr_ref, max_all_ref,
         isum_ref, dsum_ref, inum_ref, dnum_ref) = rest
        qi, ii = pl.program_id(0), pl.program_id(1)
        bn, bm = sims.shape

        @pl.when(ii == 0)
        def _():
            isum_ref[:] = jnp.zeros_like(isum_ref)
            dsum_ref[:] = jnp.zeros_like(dsum_ref)
            inum_ref[:] = jnp.zeros_like(inum_ref)
            dnum_ref[:] = jnp.zeros_like(dnum_ref)

        same, diff = _tile_masks(
            scal_ref, labels_ref, pool_labels_ref, qi, ii, bn, bm
        )
        pt = pos_thr_ref[:].T + jnp.float32(cfg.margin_ident)
        nt = neg_thr_ref[:].T + jnp.float32(cfg.margin_diff)
        sel_pos, sel_neg = _selection(sims, same, diff, pt, nt, cfg)
        sim_exp = jnp.exp(sims - max_all_ref[:].T)
        isum_ref[:] += jnp.where(sel_pos, sim_exp, 0.0).sum(1, keepdims=True).T
        dsum_ref[:] += jnp.where(sel_neg, sim_exp, 0.0).sum(1, keepdims=True).T
        inum_ref[:] += sel_pos.sum(1, keepdims=True).astype(jnp.float32).T
        dnum_ref[:] += sel_neg.sum(1, keepdims=True).astype(jnp.float32).T

    return _sim_kernel(body)(cached)


def _weight_tile(cfg, scal_ref, labels_ref, pool_labels_ref, sims,
                 pos_thr_ref, neg_thr_ref, max_all_ref,
                 isum_ref, asum_ref, valid_ref, g_ref, qi, ii):
    """w = (-p1+p2+p3) * valid * g/N for one tile (cu:405-446).

    ``sims`` is the tile's fp32 similarity block — recomputed on the MXU
    or streamed from the similarity cache by the caller.

    valid_ref is all-ones in "reference" grad mode — the reference keeps
    diff-type entries alive for identNum==0 queries (cu:133-146), so p3
    still contributes — and the zero-loss-query mask in "true" mode,
    where autodiff of the guarded log (cu:162-169) yields exactly 0.
    """
    bn, bm = sims.shape
    same, diff = _tile_masks(scal_ref, labels_ref, pool_labels_ref, qi, ii, bn, bm)
    pt = pos_thr_ref[:].T + jnp.float32(cfg.margin_ident)
    nt = neg_thr_ref[:].T + jnp.float32(cfg.margin_diff)
    sel_pos, sel_neg = _selection(sims, same, diff, pt, nt, cfg)
    # -p1+p2+p3 factors into per-query coefficients (keeps the live
    # (bn, bm) temporaries to sims/coef/w so big tiles fit VMEM):
    #   selected positive: a_q = -1/I_q + 1/(I+D)_q
    #   selected negative: b_q =          1/(I+D)_q
    # each 0-guarded per cu:412-417.
    def inv(den):
        ok = den != 0
        return jnp.where(ok, 1.0 / jnp.where(ok, den, 1.0), 0.0)

    # dot_normalizer = query count in backward (cu:427); n_real = scal[2].
    scale = (g_ref[0] / scal_ref[2].astype(jnp.float32)) * valid_ref[:].T
    a_q = (-inv(isum_ref[:].T) + inv(asum_ref[:].T)) * scale
    b_q = inv(asum_ref[:].T) * scale
    coef = jnp.where(sel_pos, a_q, jnp.where(sel_neg, b_q, 0.0))
    # Masking must be where-based, not multiplicative: a query with no
    # pairs has max_all = -FLT_MAX, so sim_exp overflows to +inf and
    # inf * 0 would poison the gemms with NaN (same hazard the dense
    # path guards, cu:152-154 semantics).
    return jnp.where(
        sel_pos | sel_neg, jnp.exp(sims - max_all_ref[:].T) * coef, 0.0
    )


def _make_gq_kernel(cfg: NPairLossConfig, cached: bool = False):
    def body(scal_ref, labels_ref, pool_labels_ref, sims, pool_ref, rest):
        (pos_thr_ref, neg_thr_ref, max_all_ref, isum_ref, asum_ref,
         valid_ref, g_ref, gq_ref) = rest
        # grid = (num_q_blocks, num_pool_blocks): pool axis accumulates.
        qi, ii = pl.program_id(0), pl.program_id(1)

        @pl.when(ii == 0)
        def _():
            gq_ref[:] = jnp.zeros_like(gq_ref)

        w = _weight_tile(
            cfg, scal_ref, labels_ref, pool_labels_ref, sims,
            pos_thr_ref, neg_thr_ref, max_all_ref, isum_ref, asum_ref,
            valid_ref, g_ref, qi, ii,
        )
        gq_ref[:] += jnp.dot(
            w, pool_ref[:],
            preferred_element_type=jnp.float32,
            precision=active_matmul_precision(),
        )

    return _sim_kernel(body, extra="pool")(cached)


def _make_gdb_kernel(cfg: NPairLossConfig, cached: bool = False):
    def body(scal_ref, labels_ref, pool_labels_ref, sims, feats_ref, rest):
        (pos_thr_ref, neg_thr_ref, max_all_ref, isum_ref, asum_ref,
         valid_ref, g_ref, gdb_ref) = rest
        # grid = (num_pool_blocks, num_q_blocks): query axis accumulates.
        ii, qi = pl.program_id(0), pl.program_id(1)

        @pl.when(qi == 0)
        def _():
            gdb_ref[:] = jnp.zeros_like(gdb_ref)

        w = _weight_tile(
            cfg, scal_ref, labels_ref, pool_labels_ref, sims,
            pos_thr_ref, neg_thr_ref, max_all_ref, isum_ref, asum_ref,
            valid_ref, g_ref, qi, ii,
        )
        gdb_ref[:] += jnp.dot(
            w.T, feats_ref[:],
            preferred_element_type=jnp.float32,
            precision=active_matmul_precision(),
        )

    return _sim_kernel(body, extra="feats")(cached)


# ---------------------------------------------------------------------------
# Host-side wrappers
# ---------------------------------------------------------------------------


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _qblock(shape, qpos: int):
    """Matrix BlockSpec indexed by the grid's query axis at ``qpos``."""
    if qpos == 0:
        return pl.BlockSpec(shape, lambda q, i: (q, 0), memory_space=pltpu.VMEM)
    return pl.BlockSpec(shape, lambda i, q: (q, 0), memory_space=pltpu.VMEM)


def _qvec(b: int, qpos: int):
    """(1, b) row-vector BlockSpec indexed by the grid's query axis."""
    if qpos == 0:
        return pl.BlockSpec((1, b), lambda q, i: (0, q), memory_space=pltpu.VMEM)
    return pl.BlockSpec((1, b), lambda i, q: (0, q), memory_space=pltpu.VMEM)


def _pblock(shape, ppos: int):
    """Matrix BlockSpec indexed by the grid's pool axis at ``ppos``."""
    if ppos == 0:
        return pl.BlockSpec(shape, lambda i, q: (i, 0), memory_space=pltpu.VMEM)
    return pl.BlockSpec(shape, lambda q, i: (i, 0), memory_space=pltpu.VMEM)


def _pvec(b: int, ppos: int):
    """(1, b) row-vector BlockSpec indexed by the grid's pool axis."""
    if ppos == 0:
        return pl.BlockSpec((1, b), lambda i, q: (0, i), memory_space=pltpu.VMEM)
    return pl.BlockSpec((1, b), lambda q, i: (0, i), memory_space=pltpu.VMEM)


def _data_specs(bn: int, bm: int, dim: int, q_axis: int):
    """Specs for (scalars, feats, labels, pool, pool_labels) with the
    query axis at grid position ``q_axis`` (pool axis at the other)."""
    p_axis = 1 - q_axis
    return [
        _smem_spec(),
        _qblock((bn, dim), q_axis),
        _qvec(bn, q_axis),
        _pblock((bm, dim), p_axis),
        _pvec(bm, p_axis),
    ]


def _simblock(bn: int, bm: int, q_axis: int):
    """(bn, bm) tile of the cached N x M similarity matrix, query axis at
    grid position ``q_axis``."""
    if q_axis == 0:
        return pl.BlockSpec(
            (bn, bm), lambda q, i: (q, i), memory_space=pltpu.VMEM
        )
    return pl.BlockSpec(
        (bn, bm), lambda i, q: (q, i), memory_space=pltpu.VMEM
    )


def _cached_data_specs(bn: int, bm: int, q_axis: int):
    """Specs for (scalars, labels, pool_labels, sims_cache) — the cached
    sweeps stream sim tiles instead of feats/pool operands."""
    return [
        _smem_spec(),
        _qvec(bn, q_axis),
        _pvec(bm, 1 - q_axis),
        _simblock(bn, bm, q_axis),
    ]


def _hist_block(bn: int):
    """(RADIX_BINS, bn) histogram BlockSpec indexed by the grid's query
    axis (bins on sublanes, queries on lanes)."""
    return pl.BlockSpec(
        (RADIX_BINS, bn), lambda q, i: (0, q), memory_space=pltpu.VMEM
    )


def _run_stats(feats_p, labels_p, pool_p, pool_labels_p, scal,
               bn, bm, interpret, hist_same=False, hist_diff=False,
               emit_sims=False, topk_same=0):
    npq, dim = feats_p.shape[0] // bn, feats_p.shape[1]
    npi = pool_p.shape[0] // bm
    n_p, m_p = feats_p.shape[0], pool_p.shape[0]
    n_hists = int(hist_same) + int(hist_diff)
    out_specs = [_qvec(bn, 0)] * 5 + [_hist_block(bn)] * n_hists
    out_shape = (
        [jax.ShapeDtypeStruct((1, n_p), jnp.float32)] * 3
        + [jax.ShapeDtypeStruct((1, n_p), jnp.int32)] * 2
        + [jax.ShapeDtypeStruct((RADIX_BINS, n_p), jnp.int32)] * n_hists
    )
    if topk_same:
        out_specs.append(pl.BlockSpec(
            (topk_same, bn), lambda q, i: (0, q), memory_space=pltpu.VMEM
        ))
        out_shape.append(
            jax.ShapeDtypeStruct((topk_same, n_p), jnp.float32))
    if emit_sims:
        out_specs.append(_simblock(bn, bm, 0))
        out_shape.append(jax.ShapeDtypeStruct((n_p, m_p), jnp.float32))
    out = pl.pallas_call(
        _make_stats_kernel(hist_same, hist_diff, emit_sims, topk_same),
        grid=(npq, npi),
        in_specs=_data_specs(bn, bm, dim, 0),
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(scal, feats_p, _row(labels_p), pool_p, _row(pool_labels_p))
    flat = [o[0, :] for o in out[:5]]
    sims_cache = out[-1] if emit_sims else None
    topk = out[5 + n_hists].T if topk_same else None  # -> [n_p, K]
    hists = [o.T for o in out[5:5 + n_hists]]  # -> [n_p, RADIX_BINS]
    h_s = hists.pop(0) if hist_same else None
    h_d = hists.pop(0) if hist_diff else None
    return (*flat, h_s, h_d, topk, sims_cache)


def _run_hist(feats_p, labels_p, pool_p, pool_labels_p, scal,
              use_same_flags, prefixes_p, digit, bn, bm, interpret,
              sims_cache=None):
    """One fused sweep -> per-side [n_p, RADIX_BINS] digit histograms."""
    npq, dim = feats_p.shape[0] // bn, feats_p.shape[1]
    npi = pool_p.shape[0] // bm
    n_p = feats_p.shape[0]
    k = len(use_same_flags)
    cached = sims_cache is not None
    if cached:
        in_specs = _cached_data_specs(bn, bm, 0) + [_qvec(bn, 0)] * k
        args = (scal, _row(labels_p), _row(pool_labels_p), sims_cache,
                *[_row(p) for p in prefixes_p])
    else:
        in_specs = _data_specs(bn, bm, dim, 0) + [_qvec(bn, 0)] * k
        args = (scal, feats_p, _row(labels_p), pool_p, _row(pool_labels_p),
                *[_row(p) for p in prefixes_p])
    out = pl.pallas_call(
        _make_hist_kernel(tuple(use_same_flags), digit, cached),
        grid=(npq, npi),
        in_specs=in_specs,
        out_specs=[_hist_block(bn)] * k,
        out_shape=[
            jax.ShapeDtypeStruct((RADIX_BINS, n_p), jnp.int32)
        ] * k,
        interpret=interpret,
    )(*args)
    return [o.T for o in out]


def _run_loss(feats_p, labels_p, pool_p, pool_labels_p, scal,
              pos_thr_p, neg_thr_p, max_all_p, cfg, bn, bm, interpret,
              sims_cache=None):
    npq, dim = feats_p.shape[0] // bn, feats_p.shape[1]
    npi = pool_p.shape[0] // bm
    cached = sims_cache is not None
    if cached:
        specs = _cached_data_specs(bn, bm, 0) + [_qvec(bn, 0)] * 3
        args = (scal, _row(labels_p), _row(pool_labels_p), sims_cache,
                _row(pos_thr_p), _row(neg_thr_p), _row(max_all_p))
    else:
        specs = _data_specs(bn, bm, dim, 0) + [_qvec(bn, 0)] * 3
        args = (scal, feats_p, _row(labels_p), pool_p, _row(pool_labels_p),
                _row(pos_thr_p), _row(neg_thr_p), _row(max_all_p))
    out = pl.pallas_call(
        _make_loss_kernel(cfg, cached),
        grid=(npq, npi),
        in_specs=specs,
        out_specs=[_qvec(bn, 0)] * 4,
        out_shape=[jax.ShapeDtypeStruct((1, feats_p.shape[0]), jnp.float32)] * 4,
        interpret=interpret,
    )(*args)
    return tuple(o[0, :] for o in out)


def _run_bwd(feats_p, labels_p, pool_p, pool_labels_p, scal,
             pos_thr_p, neg_thr_p, max_all_p, ident_sum_p, all_sum_p,
             valid_p, g, cfg, bn, bm, interpret, sims_cache=None):
    npq, dim = feats_p.shape[0] // bn, feats_p.shape[1]
    npi = pool_p.shape[0] // bm
    g_arr = jnp.asarray(g, jnp.float32).reshape(1)
    cached = sims_cache is not None
    qvecs = (
        _row(pos_thr_p), _row(neg_thr_p), _row(max_all_p),
        _row(ident_sum_p), _row(all_sum_p), _row(valid_p), g_arr,
    )
    if cached:
        # gq still streams pool tiles (for w @ pool); gdb streams feats
        # (for w^T @ feats) — but neither recomputes the sim matmul.
        gq_args = (scal, _row(labels_p), _row(pool_labels_p), sims_cache,
                   pool_p) + qvecs
        gq_specs = (_cached_data_specs(bn, bm, 0) + [_pblock((bm, dim), 1)]
                    + [_qvec(bn, 0)] * 6 + [_smem_spec()])
        gdb_args = (scal, _row(labels_p), _row(pool_labels_p), sims_cache,
                    feats_p) + qvecs
        gdb_specs = (_cached_data_specs(bn, bm, 1) + [_qblock((bn, dim), 1)]
                     + [_qvec(bn, 1)] * 6 + [_smem_spec()])
    else:
        gq_args = (scal, feats_p, _row(labels_p), pool_p,
                   _row(pool_labels_p)) + qvecs
        gq_specs = (_data_specs(bn, bm, dim, 0)
                    + [_qvec(bn, 0)] * 6 + [_smem_spec()])
        gdb_args = gq_args
        gdb_specs = (_data_specs(bn, bm, dim, 1)
                     + [_qvec(bn, 1)] * 6 + [_smem_spec()])
    gq = pl.pallas_call(
        _make_gq_kernel(cfg, cached),
        grid=(npq, npi),
        in_specs=gq_specs,
        out_specs=_qblock((bn, dim), 0),
        out_shape=jax.ShapeDtypeStruct((feats_p.shape[0], dim), jnp.float32),
        interpret=interpret,
    )(*gq_args)
    gdb = pl.pallas_call(
        _make_gdb_kernel(cfg, cached),
        grid=(npi, npq),
        in_specs=gdb_specs,
        out_specs=_pblock((bm, dim), 0),
        out_shape=jax.ShapeDtypeStruct((pool_p.shape[0], dim), jnp.float32),
        interpret=interpret,
    )(*gdb_args)
    return gq, gdb


# ---------------------------------------------------------------------------
# Streamed RELATIVE_* thresholds: exact MSD radix selection over tiles
# ---------------------------------------------------------------------------


def _thresholds(feats_p, labels_p, pool_p, pool_labels_p, scal,
                min_w, max_b, cnt_s, cnt_d, h0_s, h0_d,
                cfg, bn, bm, interpret, n, sims_cache=None,
                topk_same=None):
    """(pos_thr, neg_thr) for ANY mining config: absolute methods from the
    streamed min/max stats, RELATIVE_* via exact stepwise radix selection.

    Reproduces the dense ``_local/_global_relative_threshold`` semantics
    (ascending sort + ``_relative_pos`` index + ``< 0 -> -FLT_MAX``
    clamp, reference cu:275-337) via ops.rank_select, entirely inside
    Pallas sweeps: the digit-0 histograms ride the stats kernel for free
    (digit 0 needs no prefix), and each remaining digit is one fused
    ``_make_hist_kernel`` sweep — sim tile on the MXU, prefix-matched
    compare-and-reduce histogram on the VPU, shared across the AP and AN
    sides.  So relative mining costs NUM_DIGITS - 1 extra kernel sweeps
    whether one or both sides are relative.  GLOBAL ranks over the whole
    flattened population (cu:296, cu:327), LOCAL per query; populations
    beyond 2^31 pairs need 64-bit counts (jax_enable_x64) or fail loudly
    at trace time.

    ``topk_same`` ([n_p, K] kernel-extracted K-largest same-label sims,
    or None) arms the sparse-positive fast path: identity-balanced
    batches give each query only a handful of positives, so when every
    ``cnt_s`` fits the K-slot buffer the AP threshold is an N x K sort
    (``topk_relative_threshold``) and the AP side drops out of the
    digit sweeps entirely — the flagship GLOBAL/RELATIVE_HARD config
    then costs the same sweeps as absolute mining.  A ``lax.cond``
    falls back to the radix path at runtime when some label group
    overflows the buffer, so arbitrary label multiplicity stays exact.
    """
    pos_thr, neg_thr = absolute_thresholds(min_w, max_b, cfg)
    ap_rel = cfg.ap_mining_method in _RELATIVE
    an_rel = cfg.an_mining_method in _RELATIVE
    if not (ap_rel or an_rel):
        return pos_thr, neg_thr

    # Fast path only pays off when AP is the ONLY relative side: the
    # digit sweeps are shared across sides, so with AN also relative
    # dropping AP saves zero sweeps while doubling the cond's compiled
    # pipeline.  _blockwise_fwd_impl skips the buffer in that case too.
    if ap_rel and not an_rel and topk_same is not None:
        def radix(include_ap):
            return _radix_thresholds(
                feats_p, labels_p, pool_p, pool_labels_p, scal,
                pos_thr, neg_thr, cnt_s, cnt_d, h0_s, h0_d,
                cfg, bn, bm, interpret, n, sims_cache,
                include_ap=include_ap, include_an=an_rel)

        kcap = topk_same.shape[1]
        fits = cnt_s.max() <= kcap

        def fast(_):
            p = topk_relative_threshold(
                topk_same[:n], cnt_s, cfg.identsn, cfg.ap_mining_region,
                count_dtype=population_count_dtype(n * n))
            return p, radix(False)[1]

        return jax.lax.cond(fits, fast, lambda _: radix(True), 0)

    return _radix_thresholds(
        feats_p, labels_p, pool_p, pool_labels_p, scal,
        pos_thr, neg_thr, cnt_s, cnt_d, h0_s, h0_d,
        cfg, bn, bm, interpret, n, sims_cache,
        include_ap=ap_rel, include_an=an_rel)


def _radix_thresholds(feats_p, labels_p, pool_p, pool_labels_p, scal,
                      pos_thr, neg_thr, cnt_s, cnt_d, h0_s, h0_d,
                      cfg, bn, bm, interpret, n, sims_cache,
                      include_ap, include_an):
    """The streamed radix-selection path of ``_thresholds`` (see there),
    restricted to the requested sides."""
    sides = {}
    if include_ap:
        sides["ap"] = (True, cfg.identsn, cfg.ap_mining_region, cnt_s, h0_s)
    if include_an:
        sides["an"] = (False, cfg.diffsn, cfg.an_mining_region, cnt_d, h0_d)
    if not sides:
        return pos_thr, neg_thr

    def prep_hist(side, hist):
        _, _, region, _, _ = sides[side]
        hist = hist[:n]
        if region == MiningRegion.GLOBAL:
            cdt = population_count_dtype(n * n)
            hist = jnp.broadcast_to(
                hist.sum(axis=0, keepdims=True, dtype=cdt), (n, RADIX_BINS)
            )
        return hist

    states, empties = {}, {}
    for s, (use_same, sn, region, counts, hist0) in sides.items():
        if region == MiningRegion.GLOBAL:
            # Self-pool population is at most n x n pairs; beyond int32
            # the counts (and the rank walk) must be 64-bit or fail.
            cdt = population_count_dtype(n * n)
            total = counts.astype(cdt).sum()
            k = jnp.broadcast_to(_relative_pos(total[None], sn)[0], (n,))
            empties[s] = jnp.broadcast_to(total == 0, (n,))
        else:
            k = _relative_pos(counts, sn)
            empties[s] = counts == 0
        states[s] = radix_update(radix_begin(k), prep_hist(s, hist0))

    names = list(sides)
    use_same_flags = [sides[s][0] for s in names]
    for digit in range(1, NUM_DIGITS):
        prefixes_p = [_pad_rows(states[s][1], bn) for s in names]
        hists = _run_hist(
            feats_p, labels_p, pool_p, pool_labels_p, scal,
            use_same_flags, prefixes_p, digit, bn, bm, interpret,
            sims_cache=sims_cache,
        )
        for s, h in zip(names, hists):
            states[s] = radix_update(states[s], prep_hist(s, h))

    vals = {
        s: _clamp_negative(radix_finish(states[s], empties[s]))
        for s in sides
    }
    return vals.get("ap", pos_thr), vals.get("an", neg_thr)


# ---------------------------------------------------------------------------
# Public API: self-pool loss with custom VJP (dense-path parity, G = 1)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8))
def _blockwise_core(features, labels, cfg, bn, bm, interpret, cache,
                    pos_topk, matmul_precision):
    out, _ = _blockwise_fwd_impl(
        features, labels, cfg, bn, bm, interpret, cache, pos_topk,
        matmul_precision
    )
    return out


def _blockwise_fwd_impl(features, labels, cfg, bn, bm, interpret, cache,
                        pos_topk=0, matmul_precision=None):
    with _precision_ctx(matmul_precision):
        return _blockwise_fwd_traced(
            features, labels, cfg, bn, bm, interpret, cache, pos_topk)


def _blockwise_fwd_traced(features, labels, cfg, bn, bm, interpret, cache,
                          pos_topk=0):
    features = features.astype(jnp.float32)
    labels_i = _canon_labels(labels)
    n = features.shape[0]
    feats_p = _pad_rows(features, bn)
    labels_qp = _pad_rows(labels_i, bn)
    pool_p = _pad_rows(features, bm)
    pool_labels_p = _pad_rows(labels_i, bm)
    scal = jnp.array([n, 0, n], jnp.int32)  # [m_real, self_offset, n_real]

    ap_rel = cfg.ap_mining_method in _RELATIVE
    an_rel = cfg.an_mining_method in _RELATIVE
    (min_w, max_b, max_all, cnt_s, cnt_d, h0_s, h0_d, topk_same,
     sims_cache) = _run_stats(
        feats_p, labels_qp, pool_p, pool_labels_p, scal, bn, bm, interpret,
        hist_same=ap_rel,
        hist_diff=an_rel,
        emit_sims=cache,
        # The buffer only pays when AP is the sole relative side (see
        # _thresholds).
        topk_same=pos_topk if ap_rel and not an_rel else 0,
    )
    min_w, max_b, max_all = min_w[:n], max_b[:n], max_all[:n]
    pos_thr, neg_thr = _thresholds(
        feats_p, labels_qp, pool_p, pool_labels_p, scal,
        min_w, max_b, cnt_s[:n], cnt_d[:n], h0_s, h0_d,
        cfg, bn, bm, interpret, n, sims_cache=sims_cache,
        topk_same=topk_same,
    )
    out = _run_loss(
        feats_p, labels_qp, pool_p, pool_labels_p, scal,
        _pad_rows(pos_thr, bn), _pad_rows(neg_thr, bn), _pad_rows(max_all, bn),
        cfg, bn, bm, interpret, sims_cache=sims_cache,
    )
    isum, dsum, inum, dnum = (o[:n] for o in out)
    all_sum = isum + dsum
    valid = (isum != 0) & (all_sum != 0)
    log_q = jnp.where(valid, jnp.log(jnp.where(valid, isum / all_sum, 1.0)), 0.0)
    loss = -log_q.sum() / jnp.float32(n)

    aux = {
        "ident_num": inum,
        "diff_num": dnum,
        "pos_threshold": pos_thr,
        "neg_threshold": neg_thr,
    }
    residuals = {
        "features": features,
        "labels": labels,
        "pos_thr": pos_thr,
        "neg_thr": neg_thr,
        "max_all": max_all,
        "ident_sum": isum,
        "all_sum": all_sum,
        # The cached sim tiles ride the residuals so the backward sweeps
        # read instead of recomputing; None when caching is off.
        "sims": sims_cache,
    }
    return (loss, aux), residuals


def _blockwise_fwd(features, labels, cfg, bn, bm, interpret, cache,
                   pos_topk, matmul_precision):
    return _blockwise_fwd_impl(
        features, labels, cfg, bn, bm, interpret, cache, pos_topk,
        matmul_precision
    )


def _blockwise_bwd(cfg, bn, bm, interpret, cache, pos_topk,
                   matmul_precision, res, cotangents):
    with _precision_ctx(matmul_precision):
        return _blockwise_bwd_traced(
            cfg, bn, bm, interpret, cache, pos_topk, res, cotangents)


def _blockwise_bwd_traced(cfg, bn, bm, interpret, cache, pos_topk, res,
                          cotangents):
    g, _ = cotangents  # aux outputs are monitors
    features = res["features"]
    labels = res["labels"]
    labels_i = _canon_labels(labels)
    n = features.shape[0]
    if cfg.grad_mode == "reference":
        valid = jnp.ones((n,), jnp.float32)
    else:
        valid = (
            (res["ident_sum"] != 0) & (res["all_sum"] != 0)
        ).astype(jnp.float32)
    scal = jnp.array([n, 0, n], jnp.int32)
    gq, gdb = _run_bwd(
        _pad_rows(features, bn), _pad_rows(labels_i, bn),
        _pad_rows(features, bm), _pad_rows(labels_i, bm), scal,
        _pad_rows(res["pos_thr"], bn), _pad_rows(res["neg_thr"], bn),
        _pad_rows(res["max_all"], bn), _pad_rows(res["ident_sum"], bn),
        _pad_rows(res["all_sum"], bn), _pad_rows(valid, bn),
        g, cfg, bn, bm, interpret, sims_cache=res["sims"],
    )
    gq, gdb = gq[:n], gdb[:n]
    if cfg.grad_mode == "reference":
        # G = 1 specialization of cu:462-497: allreduce is the identity,
        # 1/G = 1, own rows are the whole database grad; 0.5/0.5 merge.
        d_features = 0.5 * gdb + 0.5 * gq
    else:
        d_features = gq + gdb
    if jnp.issubdtype(labels.dtype, jnp.floating):
        d_labels = jnp.zeros(labels.shape, labels.dtype)
    else:
        d_labels = np.zeros(labels.shape, jax.dtypes.float0)
    return d_features, d_labels


_blockwise_core.defvjp(_blockwise_fwd, _blockwise_bwd)


def blockwise_npair_loss_with_aux(
    features: jax.Array,
    labels: jax.Array,
    cfg: NPairLossConfig = NPairLossConfig(),
    block_size: int = 512,
    q_block_size: Optional[int] = None,
    interpret: Optional[bool] = None,
    sim_cache: Optional[bool] = None,
    pos_topk: Optional[int] = None,
    matmul_precision: Optional[str] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """N-pair loss over a self-pool too large for the dense N x N matrix.

    Semantically identical (loss and gradient) to
    ``npair_loss_with_aux(features, labels, cfg)`` for every mining
    configuration (RELATIVE_* thresholds via streamed radix selection),
    but peak memory is O(q_block x D + block x D + q_block x
    block) VMEM per tile — the pair matrix is produced and consumed
    tile-by-tile inside Pallas kernels.  ``aux`` carries the
    streaming-computable monitors (pair counts, thresholds) — the full
    similarity matrices of the dense aux are exactly what this path
    exists to avoid.

    ``sim_cache``: materialize the fp32 sim tiles once (in the stats
    sweep) and stream them back in every later sweep instead of
    recomputing the fp32-HIGHEST matmul — bit-identical, much faster,
    but holds the N x N fp32 matrix in HBM through the step.  Default
    ``None`` auto-enables it when that matrix is at most
    ``SIM_CACHE_AUTO_BYTES``; pass ``False`` to force the O(N x block)
    streaming-memory behavior.

    ``pos_topk``: K-slot sparse-positive fast path for RELATIVE_* AP
    mining (see ``_thresholds``): the stats sweep extracts each query's
    K largest same-label sims, and when every query's positive count
    fits the buffer the AP threshold needs no digit sweeps — the
    flagship config then streams as few passes as absolute mining.  A
    runtime ``lax.cond`` falls back to radix selection when a label
    group overflows, so the result is exact for any labels.  Default
    ``None`` = auto (8 slots — covers per-query positive counts up to
    8, i.e. identity-balanced sampling with up to NINE images per
    identity in the pool); 0 disables the buffer entirely.

    ``matmul_precision``: ``None``/``"highest"`` for oracle bit-parity;
    ``"default"`` opts every kernel gemm into the ~6x single-pass bf16
    MXU mode (see ``ops.npair_loss.resolve_matmul_precision`` — a
    throughput mode, not a parity mode).
    """
    if interpret is None:
        interpret = _default_interpret()
    n = features.shape[0]
    bm = int(min(block_size, max(n, 1)))
    bn = int(min(q_block_size or block_size, max(n, 1)))
    if not interpret:
        # Mosaic requires block dims divisible by the (8, 128) tiling
        # (unless equal to the full padded dim); the block index appears
        # as both a sublane dim (matrix tiles) and a lane dim ((1, b)
        # stat vectors), so round to 128.  _pad_rows absorbs overshoot.
        bn, bm = _round_up(bn, 128), _round_up(bm, 128)
    if sim_cache is None:
        n_p, m_p = _round_up(n, bn), _round_up(n, bm)
        sim_cache = resolve_sim_cache_auto(n_p * m_p * 4, "blockwise")
    if pos_topk is None:
        pos_topk = 8
    if int(pos_topk) < 0:
        raise ValueError(f"pos_topk must be >= 0, got {pos_topk}")
    # fp32 (8, 128) tiling: the K-slot buffer's sublane dim must be a
    # multiple of 8 (extra slots just carry more padding).
    pos_topk = _round_up(int(pos_topk), 8) if pos_topk else 0
    return _blockwise_core(
        features, labels, cfg, bn, bm, interpret, bool(sim_cache),
        pos_topk, matmul_precision
    )


def blockwise_npair_loss(features, labels, cfg=NPairLossConfig(),
                         block_size: int = 512,
                         q_block_size: Optional[int] = None,
                         interpret: Optional[bool] = None,
                         sim_cache: Optional[bool] = None,
                         pos_topk: Optional[int] = None,
                         matmul_precision: Optional[str] = None) -> jax.Array:
    """Scalar blockwise N-pair loss (see ``blockwise_npair_loss_with_aux``)."""
    return blockwise_npair_loss_with_aux(
        features, labels, cfg, block_size, q_block_size, interpret,
        sim_cache, pos_topk, matmul_precision
    )[0]


# ---------------------------------------------------------------------------
# Streamed retrieval metrics (pure-JAX scan; no N x M matrix)
# ---------------------------------------------------------------------------


def blockwise_retrieval_metrics(
    features: jax.Array,
    labels: jax.Array,
    top_ks: Sequence[int] = (1, 5, 10),
    block_size: int = 512,
) -> Dict[str, jax.Array]:
    """Recall@k + feature_asum with the reference's exact threshold/tie
    semantics (cu:182-197), streaming the pool in blocks via lax.scan.

    Keeps a running top-(k_max+1) list per query (exp is monotone, so raw
    similarities give identical ranks to the reference's exp'd rows).
    """
    features = features.astype(jnp.float32)
    labels = _canon_labels(labels)
    n = features.shape[0]
    neg = jnp.float32(-FLT_MAX)
    k_max = max(top_ks)
    block = int(min(block_size, max(n, 1)))
    pool = _pad_rows(features, block)
    pool_labels = _pad_rows(labels, block)
    nblocks = pool.shape[0] // block
    pool = pool.reshape(nblocks, block, -1)
    pool_labels = pool_labels.reshape(nblocks, block)

    def step(carry, blk):
        top_sims, top_same = carry
        bf, bl, idx = blk
        sims = jnp.dot(
            features, bf.T,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        col = idx * block + jnp.arange(block, dtype=jnp.int32)[None, :]
        row = jnp.arange(n, dtype=jnp.int32)[:, None]
        nonself = (col != row) & (col < n)
        same = (labels[:, None] == bl[None, :]) & nonself
        cat_sims = jnp.concatenate(
            [top_sims, jnp.where(nonself, sims, neg)], axis=1
        )
        cat_same = jnp.concatenate([top_same, same], axis=1)
        top_sims, idx2 = jax.lax.top_k(cat_sims, top_sims.shape[1])
        top_same = jnp.take_along_axis(cat_same, idx2, axis=1)
        return (top_sims, top_same), None

    carry = (
        jnp.full((n, k_max + 1), neg),
        jnp.zeros((n, k_max + 1), bool),
    )
    (top_sims, top_same), _ = jax.lax.scan(
        step, carry,
        (pool, pool_labels, jnp.arange(nblocks, dtype=jnp.int32)),
    )

    out: Dict[str, jax.Array] = {}
    for k in top_ks:
        thr_idx = min(k, n - 2)
        thr = top_sims[:, thr_idx]
        hit = jnp.any((top_sims > thr[:, None]) & top_same, axis=1)
        out[f"retrieve_top{k}"] = hit.sum().astype(jnp.float32) / jnp.float32(n)
    out["feature_asum"] = jnp.abs(features).sum() / jnp.float32(n)
    return out
