"""Pallas TPU kernels for the GoogLeNet stem's VPU-bound tail.

The perf observatory (``prof --step train``, obs/perf) attributes the
flagship trunk's non-MXU time to the stem's elementwise chain: the two
across-channel LRN layers (square -> windowed sum -> pow -> scale — a
VPU reduce XLA cannot fuse into any matmul, measured at ~25% of the
prototxt-parity step, PROFILE.md) and the conv epilogues (bias + ReLU,
bias + ReLU + 3x3/s2 max-pool) whose intermediates XLA materializes to
HBM between the conv gemm and the pool reduce.  These kernels fuse each
chain into ONE VMEM pass:

* :func:`fused_lrn`        — x^2 -> channel-window sum -> rsqrt-pow ->
  scale in a single tile visit, with an analytic custom VJP whose
  backward is a second one-pass kernel (the transpose window).
* :func:`fused_bias_relu`  — conv epilogue: bias add + ReLU fused (the
  conv itself stays an XLA gemm — the MXU half is already optimal).
* :func:`fused_bias_relu_pool` — stem epilogue: bias + ReLU + max-pool
  in one pass, so the pre-pool activation never round-trips HBM.

**Denominator cache** (the ``sim_cache`` pattern of
``ops/pallas_npair.py`` transplanted): the LRN backward needs the
forward's denominator ``d = k + a*W(x^2)``.  When the fp32 ``d`` tensor
fits the auto budget (``LRN_CACHE_AUTO_BYTES``), the forward kernel
writes it out once and the backward streams it back (``cache=True``);
beyond the budget the backward recomputes the window sum from ``x``
(``cache=False``) — one extra VPU pass instead of an HBM-resident
tensor.  Cached and recompute paths are bit-identical (the cache stores
exactly the fp32 values the forward produced); ``cache=None`` picks by
size, mirroring ``resolve_sim_cache_auto``.

On non-TPU backends every kernel runs in Pallas interpreter mode, which
is how the CPU suite checks parity against the XLA reference
(``models.layers.local_response_norm`` / bias+relu+``reduce_window``)
— forward AND backward, including ragged row/channel tiles
(tests/test_pallas_stem.py).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (TPU lowering)

# fp32 bytes of the LRN denominator tensor below which the forward
# caches it for the backward (the pallas_npair SIM_CACHE_AUTO_BYTES
# pattern at stem-activation scale: the batch-120 pool1 site is ~385 MB
# — cached on a 16 GB chip, recomputed only when an operator forces
# cache=False or the tensor outgrows the budget at very large batch).
LRN_CACHE_AUTO_BYTES = 2 << 30

_BLOCK_ROWS = 256
_LANES = 128


def _default_interpret() -> bool:
    return jax.default_backend() != "tpu"


def resolve_lrn_cache_auto(nbytes: int, cache: Optional[bool]) -> bool:
    """Explicit ``cache`` wins; None = auto by the fp32 denominator
    size (same contract shape as ops.npair_loss.resolve_sim_cache_auto)."""
    if cache is not None:
        return bool(cache)
    return nbytes <= LRN_CACHE_AUTO_BYTES


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


def _pad2d(x: jax.Array, rows: int, cols: int) -> jax.Array:
    r, c = x.shape
    if r == rows and c == cols:
        return x
    return jnp.pad(x, ((0, rows - r), (0, cols - c)))


def _win_sum(v: jax.Array, lo: int, hi: int) -> jax.Array:
    """Channel-axis windowed sum with zero fill: out[:, i] =
    sum_{d=-lo..hi} v[:, i+d].  Static shapes (lo+hi+1 shifted adds) —
    the in-register form of the reduce_window the XLA reference uses.
    Zero fill matches reduce_window's zero padding, and the zero-padded
    channel tail (c..cpad) contributes zeros exactly like the columns
    beyond the real C would."""
    c = v.shape[1]
    vp = jnp.pad(v, ((0, 0), (lo, hi)))
    out = vp[:, 0:c]
    for o in range(1, lo + hi + 1):
        out = out + vp[:, o:o + c]
    return out


def _d_pow_negbeta(d: jax.Array, beta: float) -> jax.Array:
    """d^-beta; beta=0.75 uses the two-fast-VPU-op identity
    (sqrt(rsqrt(d)))^3 the XLA reference uses (models/layers.py), so
    the kernel stays bit-comparable to it."""
    if beta == 0.75:
        r = jnp.sqrt(jax.lax.rsqrt(d))
        return r * r * r
    return jnp.exp(jnp.float32(-beta) * jnp.log(d))


class _LRNParams(NamedTuple):
    """Hashable nondiff bundle for the custom_vjp (trace-time config)."""

    size: int
    alpha: float
    beta: float
    k: float
    cached: bool
    interpret: bool


# -- LRN forward/backward kernels -------------------------------------------


def _lrn_fwd_kernel(x_ref, o_ref, *, p: _LRNParams):
    x = x_ref[:].astype(jnp.float32)
    win = _win_sum(x * x, p.size // 2, p.size - 1 - p.size // 2)
    d = p.k + (p.alpha / p.size) * win
    o_ref[:] = (x * _d_pow_negbeta(d, p.beta)).astype(o_ref.dtype)


def _lrn_fwd_cached_kernel(x_ref, o_ref, d_ref, *, p: _LRNParams):
    x = x_ref[:].astype(jnp.float32)
    win = _win_sum(x * x, p.size // 2, p.size - 1 - p.size // 2)
    d = p.k + (p.alpha / p.size) * win
    d_ref[:] = d
    o_ref[:] = (x * _d_pow_negbeta(d, p.beta)).astype(o_ref.dtype)


def _lrn_bwd_kernel(x_ref, g_ref, o_ref, *, p: _LRNParams):
    """dx from (x, g), recomputing d (cache=False).

    With y_i = x_i d_i^-b and d_i = k + a * W(x^2)_i (W the forward
    window, a = alpha/size):
        dx_j = g_j d_j^-b - 2ab x_j * W^T(g x d^{-b-1})_j
    where W^T is the window with (lo, hi) swapped — symmetric for odd
    sizes, exact either way."""
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    win = _win_sum(x * x, p.size // 2, p.size - 1 - p.size // 2)
    d = p.k + (p.alpha / p.size) * win
    o_ref[:] = _lrn_bwd_math(x, g, d, p).astype(o_ref.dtype)


def _lrn_bwd_cached_kernel(x_ref, g_ref, d_ref, o_ref, *, p: _LRNParams):
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    o_ref[:] = _lrn_bwd_math(x, g, d_ref[:], p).astype(o_ref.dtype)


def _lrn_bwd_math(x, g, d, p: _LRNParams):
    f = _d_pow_negbeta(d, p.beta)
    # g * x * d^{-b-1}, then the TRANSPOSE window (hi, lo swapped).
    t = _win_sum(g * x * (f / d),
                 p.size - 1 - p.size // 2, p.size // 2)
    return g * f - (2.0 * p.alpha / p.size * p.beta) * x * t


def _lrn_grid(rpad: int, cpad: int):
    """(grid, block_rows) over the PADDED row count (``_lrn_pad_geometry``
    guarantees rpad is either < _BLOCK_ROWS or a multiple of it)."""
    br = _BLOCK_ROWS if rpad >= _BLOCK_ROWS else rpad
    return (rpad // br,), br


def _lrn_fwd_call(x2: jax.Array, p: _LRNParams):
    """Padded 2-D forward dispatch; returns (out2, d2_or_None) at the
    PADDED geometry (the caller slices)."""
    rows, cpad = x2.shape
    grid, br = _lrn_grid(rows, cpad)
    spec = pl.BlockSpec((br, cpad), lambda i: (i, 0))
    if p.cached:
        out2, d2 = pl.pallas_call(
            functools.partial(_lrn_fwd_cached_kernel, p=p),
            grid=grid,
            in_specs=[spec],
            out_specs=(spec, spec),
            out_shape=(
                jax.ShapeDtypeStruct((rows, cpad), x2.dtype),
                jax.ShapeDtypeStruct((rows, cpad), jnp.float32),
            ),
            interpret=p.interpret,
        )(x2)
        return out2, d2
    out2 = pl.pallas_call(
        functools.partial(_lrn_fwd_kernel, p=p),
        grid=grid,
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((rows, cpad), x2.dtype),
        interpret=p.interpret,
    )(x2)
    return out2, None


def _lrn_bwd_call(x2: jax.Array, g2: jax.Array, d2: Optional[jax.Array],
                  p: _LRNParams) -> jax.Array:
    rows, cpad = x2.shape
    grid, br = _lrn_grid(rows, cpad)
    spec = pl.BlockSpec((br, cpad), lambda i: (i, 0))
    if d2 is not None:
        return pl.pallas_call(
            functools.partial(_lrn_bwd_cached_kernel, p=p),
            grid=grid,
            in_specs=[spec, spec, spec],
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct((rows, cpad), x2.dtype),
            interpret=p.interpret,
        )(x2, g2, d2)
    return pl.pallas_call(
        functools.partial(_lrn_bwd_kernel, p=p),
        grid=grid,
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((rows, cpad), x2.dtype),
        interpret=p.interpret,
    )(x2, g2)


def _lrn_pad_geometry(shape) -> Tuple[int, int, int, int]:
    """(rows, c, rpad, cpad) of the 2-D channels-last view: channels
    lane-padded to 128, rows padded to one 16-sublane block (small
    inputs) or a _BLOCK_ROWS multiple (16 divides _BLOCK_ROWS, so both
    shapes satisfy the bf16 (16, 128) min tile)."""
    c = shape[-1]
    rows = 1
    for s in shape[:-1]:
        rows *= s
    rows = max(rows, 1)
    cpad = _round_up(c, _LANES)
    if rows >= _BLOCK_ROWS:
        rpad = _round_up(rows, _BLOCK_ROWS)
    else:
        rpad = _round_up(rows, 16)
    return rows, c, rpad, cpad


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _fused_lrn(x: jax.Array, p: _LRNParams) -> jax.Array:
    # The PRIMAL body (no-grad forwards: extract/test/eval/serve) —
    # the denominator cache is purely a backward residual, so dispatch
    # uncached here; only the vjp fwd below pays for (and keeps) d.
    out, _ = _fused_lrn_fwd_impl(x, p._replace(cached=False))
    return out


def _fused_lrn_fwd_impl(x: jax.Array, p: _LRNParams):
    rows, c, rpad, cpad = _lrn_pad_geometry(x.shape)
    x2 = _pad2d(x.reshape(rows, c), rpad, cpad)
    out2, d2 = _lrn_fwd_call(x2, p)
    out = out2[:rows, :c].reshape(x.shape)
    return out, d2  # d2 stays padded — the backward re-uses it as-is


def _fused_lrn_vjp_fwd(x, p: _LRNParams):
    out, d2 = _fused_lrn_fwd_impl(x, p)
    return out, (x, d2)


def _fused_lrn_vjp_bwd(p: _LRNParams, res, g):
    x, d2 = res
    rows, c, rpad, cpad = _lrn_pad_geometry(x.shape)
    x2 = _pad2d(x.reshape(rows, c), rpad, cpad)
    g2 = _pad2d(g.reshape(rows, c).astype(x.dtype), rpad, cpad)
    dx2 = _lrn_bwd_call(x2, g2, d2, p)
    return (dx2[:rows, :c].reshape(x.shape),)


_fused_lrn.defvjp(_fused_lrn_vjp_fwd, _fused_lrn_vjp_bwd)


def fused_lrn(
    x: jax.Array,
    size: int = 5,
    alpha: float = 1e-4,
    beta: float = 0.75,
    k: float = 1.0,
    cache: Optional[bool] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Across-channel LRN (Caffe semantics, channels-last) as one fused
    Pallas pass — drop-in for ``models.layers.local_response_norm``.

    ``cache`` controls the denominator cache (None = auto by size, the
    ops/pallas_npair sim-cache pattern); ``interpret`` forces/forbids
    Pallas interpreter mode (None = auto: interpret off-TPU)."""
    if interpret is None:
        interpret = _default_interpret()
    # Budget the cache at the tensor the cached kernel ACTUALLY writes:
    # the padded (rpad, cpad) fp32 denominator (lane padding alone is
    # 2x at a C=64 site), not the logical x.size.
    _, _, rpad, cpad = _lrn_pad_geometry(x.shape)
    cached = resolve_lrn_cache_auto(rpad * cpad * 4, cache)
    p = _LRNParams(int(size), float(alpha), float(beta), float(k),
                   bool(cached), bool(interpret))
    return _fused_lrn(x, p)


# -- conv epilogues ----------------------------------------------------------


def _bias_relu_kernel(x_ref, b_ref, o_ref):
    y = x_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    o_ref[:] = jnp.maximum(y, 0.0).astype(o_ref.dtype)


class _EpiParams(NamedTuple):
    interpret: bool


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fused_bias_relu(x: jax.Array, bias: jax.Array,
                     p: _EpiParams) -> jax.Array:
    rows, c, rpad, cpad = _lrn_pad_geometry(x.shape)
    x2 = _pad2d(x.reshape(rows, c), rpad, cpad)
    b2 = _pad2d(bias.reshape(1, c), 1, cpad)
    grid, br = _lrn_grid(rpad, cpad)
    out2 = pl.pallas_call(
        _bias_relu_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((br, cpad), lambda i: (i, 0)),
            pl.BlockSpec((1, cpad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, cpad), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rpad, cpad), x.dtype),
        interpret=p.interpret,
    )(x2, b2)
    return out2[:rows, :c].reshape(x.shape)


def _fused_bias_relu_vjp_fwd(x, bias, p: _EpiParams):
    out = _fused_bias_relu(x, bias, p)
    return out, (out, bias)


def _fused_bias_relu_vjp_bwd(p: _EpiParams, res, g):
    # The backward of bias+ReLU is a mask + a channel reduce — XLA
    # fuses that chain fine on its own; the Pallas win is the forward's
    # single VMEM visit.  Residual = the OUTPUT (its sign IS the mask),
    # same bytes the XLA relu residual would hold (+ the tiny bias, for
    # its cotangent dtype — custom_vjp requires db.dtype == bias.dtype,
    # which a policy rule may set to non-fp32).
    out, bias = res
    mask = out > 0
    dx = jnp.where(mask, g, jnp.zeros_like(g))
    axes = tuple(range(g.ndim - 1))
    db = dx.astype(jnp.float32).sum(axis=axes).astype(bias.dtype)
    return dx, db


_fused_bias_relu.defvjp(_fused_bias_relu_vjp_fwd, _fused_bias_relu_vjp_bwd)


def fused_bias_relu(x: jax.Array, bias: jax.Array,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Conv epilogue: ``relu(x + bias)`` (bias broadcast over the last
    axis) in one fused VMEM pass, with an XLA backward."""
    if interpret is None:
        interpret = _default_interpret()
    return _fused_bias_relu(x, bias, _EpiParams(bool(interpret)))


def _same_pads(n: int, window: int, stride: int) -> Tuple[int, int, int]:
    """(out, pad_lo, pad_hi) of XLA SAME pooling on an axis of size n."""
    out = -(-n // stride)
    total = max((out - 1) * stride + window - n, 0)
    return out, total // 2, total - total // 2


class _PoolParams(NamedTuple):
    window: int
    stride: int
    interpret: bool


def _bias_relu_pool_kernel(x_ref, b_ref, o_ref, *, p: _PoolParams,
                           geom):
    ho, ph_lo, ph_hi, wo, pw_lo, pw_hi = geom
    y = jnp.maximum(
        x_ref[:].astype(jnp.float32)
        + b_ref[:].astype(jnp.float32).reshape(1, 1, 1, -1),
        0.0,
    )
    # SAME max-pool via static shifted strided slices.  Zero fill is
    # exact here: post-ReLU values are >= 0, so a zero pad can never
    # beat a real in-window value (and a window is never all-padding
    # under SAME), matching reduce_window's -inf-init semantics.
    yp = jnp.pad(y, ((0, 0), (ph_lo, ph_hi), (pw_lo, pw_hi), (0, 0)))
    s = p.stride
    m = None
    for di in range(p.window):
        for dj in range(p.window):
            tile = yp[:, di:di + (ho - 1) * s + 1:s,
                      dj:dj + (wo - 1) * s + 1:s, :]
            m = tile if m is None else jnp.maximum(m, tile)
    o_ref[:] = m.astype(o_ref.dtype)


def _reference_bias_relu_pool(x, bias, window: int, stride: int):
    y = jnp.maximum(x.astype(jnp.float32)
                    + bias.astype(jnp.float32), 0.0)
    out = jax.lax.reduce_window(
        y, -jnp.inf, jax.lax.max,
        (1, window, window, 1), (1, stride, stride, 1), "SAME",
    )
    return out.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fused_bias_relu_pool(x: jax.Array, bias: jax.Array,
                          p: _PoolParams) -> jax.Array:
    n, h, w, c = x.shape
    ho, ph_lo, ph_hi = _same_pads(h, p.window, p.stride)
    wo, pw_lo, pw_hi = _same_pads(w, p.window, p.stride)
    cpad = _round_up(c, _LANES)
    xp = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, cpad - c)))
    b2 = _pad2d(bias.reshape(1, c), 1, cpad)
    out = pl.pallas_call(
        functools.partial(
            _bias_relu_pool_kernel, p=p,
            geom=(ho, ph_lo, ph_hi, wo, pw_lo, pw_hi),
        ),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, w, cpad), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, cpad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, ho, wo, cpad), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, ho, wo, cpad), x.dtype),
        interpret=p.interpret,
    )(xp, b2)
    return out[..., :c]


def _fused_bias_relu_pool_vjp_fwd(x, bias, p: _PoolParams):
    return _fused_bias_relu_pool(x, bias, p), (x, bias)


def _fused_bias_relu_pool_vjp_bwd(p: _PoolParams, res, g):
    # Max-pool backward is an argmax scatter — recomputed through XLA's
    # own reduce_window VJP (the fusion win is the forward's skipped
    # HBM round-trip of the pre-pool activation; the backward pays one
    # reference recompute, like remat).
    x, bias = res
    _, vjp = jax.vjp(
        lambda xx, bb: _reference_bias_relu_pool(xx, bb, p.window,
                                                 p.stride),
        x, bias,
    )
    dx, db = vjp(g)
    return dx, db.astype(bias.dtype)


_fused_bias_relu_pool.defvjp(_fused_bias_relu_pool_vjp_fwd,
                             _fused_bias_relu_pool_vjp_bwd)


def fused_bias_relu_pool(
    x: jax.Array,
    bias: jax.Array,
    window: int = 3,
    stride: int = 2,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Stem epilogue: ``max_pool(relu(x + bias))`` (SAME padding,
    NHWC) in one fused pass — the pre-pool activation never leaves
    VMEM.  Backward recomputes through the XLA reference (remat-style)."""
    if interpret is None:
        interpret = _default_interpret()
    return _fused_bias_relu_pool(
        x, bias, _PoolParams(int(window), int(stride), bool(interpret)))
