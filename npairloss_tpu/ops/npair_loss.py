"""TPU-native multi-class N-pair metric-learning loss.

Re-implements — as ONE pure, jit-compatible JAX function — the semantics of
the reference Caffe CUDA+MPI layer ``NPairMultiClassLossLayer``
(reference: npair_multi_class_loss.cu:207-499).  Where the reference runs

    MPI_Allgather -> cuBLAS gemm -> 2 CUDA mask kernels
    -> an O(N^2 G) *CPU* mining loop with std::sort
    -> selection kernel -> exp/stabilize kernel -> gemv reductions
    -> loss kernel -> host-side metric loop,

with device<->host round-trips between every stage, this implementation is a
single XLA graph: ``jax.lax.all_gather`` over the mesh axis replaces
MPI_Allgather (cu:17-43), the similarity matrix hits the MXU as one matmul
(cu:218), mining statistics become masked fixed-shape sorts/reductions
(cu:222-337), and the loss is a numerically-stabilized masked softmax
(cu:362-388).  The analytic backward (cu:420-499) — including its
non-obvious 0.5/0.5 query-role/database-role averaging and 1/G allreduce
scaling — is provided as a ``jax.custom_vjp``.

Mining semantics grid (cu:277-337 thresholds, cu:69-122 selection):

  region  = GLOBAL(0) | LOCAL(1)                 # over this rank's N x N*G block
  method  = HARD | EASY | RAND | RELATIVE_HARD | RELATIVE_EASY

Reference quirks that are preserved bit-for-bit (each has a named test):
  * RAND selects ALL pairs — there is no randomness (cu:88-89, cu:109-110).
  * RELATIVE thresholds whose looked-up value is < 0 clamp to -FLT_MAX
    (cu:288, cu:303, cu:319, cu:334).
  * sn >= 0 means an absolute rank from the sorted top; sn < 0 means the top
    |sn| fraction, with C truncation-toward-zero (cu:285-287 etc.).
  * Zero-count queries contribute exactly 0 loss (cu:133-154, cu:162-169).
  * The self-pair (local row q == gathered column rank*N + q) is excluded
    from both masks (cu:54).
  * The backward's dot_normalizer is N (query count), while the forward's is
    1 (cu:216 vs cu:427).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import enum
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from npairloss_tpu.ops.rank_select import masked_digit_hist, radix_select

FLT_MAX = float(np.finfo(np.float32).max)

# Auto-enable a streaming engine's fp32 similarity cache when the cached
# slice is at most this many bytes.  Shared by ops.pallas_npair and
# parallel.ring.  ``resolve_sim_cache_auto`` additionally caps the
# budget at 1/5 of the device's reported HBM: round 4 found that
# DISPATCHING the cached program with the 32k pool's 4.0 GiB (4.29 GB)
# cache on a 16 GiB v5e wedges the tunneled backend outright (every
# later client gets UNAVAILABLE until the server resets).  4.0 GiB is
# EXACTLY 16 GiB / 4, so a quarter-of-HBM cap would sit at a zero
# margin; 1/5 (3.2 GiB on v5e) rejects it with real slack while still
# admitting the 24k pool's 2.25 GiB slice.  Backends that report no
# memory stats get a conservative 2 GiB budget — the hazard is a
# backend-wedging dispatch, not a recoverable OOM, so the unknown case
# fails closed.  Pass ``sim_cache=True`` to override explicitly, at
# your own risk.
SIM_CACHE_AUTO_BYTES = 6 << 30

_SIM_CACHE_LOGGED = set()


def resolve_sim_cache_auto(cache_bytes: int, engine: str) -> bool:
    """Decide whether a streaming engine's fp32 sim cache auto-enables.

    The cache rides the VJP residuals through the whole model backward,
    so the budget is sized against the device's reported memory (1/5 of
    ``bytes_limit`` — see the hazard note on ``SIM_CACHE_AUTO_BYTES`` —
    capped at that constant; a conservative 2 GiB when the backend
    reports no memory stats), and every auto-enable is logged ONCE per
    (engine, size) so an OOM regression is attributable to the cache
    (ADVICE r3).  Explicit ``sim_cache=True/False`` never reaches here.
    """
    budget = SIM_CACHE_AUTO_BYTES
    limit = 0
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
    except Exception:
        pass
    # Unknown memory fails CLOSED (the hazard is a backend-wedging
    # dispatch, not a recoverable OOM).
    budget = min(budget, limit // 5 if limit > 0 else 2 << 30)
    enable = cache_bytes <= budget
    key = (engine, cache_bytes, enable)
    if enable and key not in _SIM_CACHE_LOGGED:
        _SIM_CACHE_LOGGED.add(key)
        import logging

        logging.getLogger("npairloss_tpu").info(
            "%s: auto-enabling fp32 similarity cache (%.0f MiB <= budget "
            "%.0f MiB); pass sim_cache=False if HBM-tight",
            engine, cache_bytes / 2**20, budget / 2**20,
        )
    return enable


class MiningRegion(enum.IntEnum):
    """Where a threshold is computed (caffe.proto:8-11)."""

    GLOBAL = 0  # one threshold from this rank's whole N x N*G block
    LOCAL = 1  # a per-query threshold


class MiningMethod(enum.IntEnum):
    """How pairs are selected against the threshold (caffe.proto:12-18)."""

    HARD = 0
    EASY = 1
    RAND = 2  # reference quirk: selects ALL pairs, no randomness (cu:88,109)
    RELATIVE_HARD = 3
    RELATIVE_EASY = 4


_RELATIVE = (MiningMethod.RELATIVE_HARD, MiningMethod.RELATIVE_EASY)
_ABSOLUTE = (MiningMethod.HARD, MiningMethod.EASY, MiningMethod.RAND)


@dataclasses.dataclass(frozen=True)
class NPairLossConfig:
    """Static loss configuration — mirrors NPairLossParameter (caffe.proto:3-23).

    Defaults match the proto defaults exactly.
    """

    margin_ident: float = 0.0
    margin_diff: float = 0.0
    identsn: float = -1.0
    diffsn: float = -1.0
    ap_mining_region: MiningRegion = MiningRegion.LOCAL
    ap_mining_method: MiningMethod = MiningMethod.RAND
    an_mining_region: MiningRegion = MiningRegion.LOCAL
    an_mining_method: MiningMethod = MiningMethod.RAND
    # Gradient semantics. "reference" reproduces cu:420-499 exactly:
    #   dF_local = 0.5 * query-role grad + 0.5 * (1/G) * psum(database-role grad)
    # "true" lets JAX autodiff produce the mathematically exact gradient of the
    # mean loss (query-role + database-role summed, no 0.5/1G rescale).
    grad_mode: str = "reference"

    def __post_init__(self):
        if self.grad_mode not in ("reference", "true"):
            raise ValueError(
                f"grad_mode must be 'reference' or 'true', got {self.grad_mode!r}"
            )


# The exact mining configuration the reference ships (usage/def.prototxt:
# 137-146): all positives at-or-below the block-wide top similarity (i.e.
# every positive), negatives harder than the per-query hardest positive
# minus 0.05.
REFERENCE_CONFIG = NPairLossConfig(
    margin_ident=0.0,
    margin_diff=-0.05,
    identsn=-0.0,
    diffsn=-0.3,
    ap_mining_region=MiningRegion.GLOBAL,
    ap_mining_method=MiningMethod.RELATIVE_HARD,
    an_mining_region=MiningRegion.LOCAL,
    an_mining_method=MiningMethod.HARD,
)


# ---------------------------------------------------------------------------
# Mask construction (reference: GetLabelDiffMtx kernel, cu:44-66)
# ---------------------------------------------------------------------------


def pair_masks(
    local_labels: jax.Array, total_labels: jax.Array, rank: jax.Array, n_local: int
) -> Tuple[jax.Array, jax.Array]:
    """Same-label / different-label 0-1 masks over the N x (N*G) pair grid.

    The self pair — local row q against gathered column ``rank*n_local + q`` —
    is excluded from both masks (cu:54).
    """
    same_lbl = local_labels[:, None] == total_labels[None, :]
    col = jnp.arange(total_labels.shape[0], dtype=jnp.int32)[None, :]
    row_global = jnp.arange(n_local, dtype=jnp.int32)[:, None] + rank * n_local
    not_self = col != row_global
    same = same_lbl & not_self
    diff = (~same_lbl) & not_self
    return same, diff


# ---------------------------------------------------------------------------
# Mining statistics + threshold selection (cu:222-337)
# ---------------------------------------------------------------------------


def _relative_pos(count: jax.Array, sn: float) -> jax.Array:
    """Sorted-list index for RELATIVE_{HARD,EASY} mining.

    The reference indexes an ascending-sorted similarity list with
      sn >= 0 : size - 1 - int(sn)            (absolute rank from the top)
      sn <  0 : int(size - 1 + sn * size)     (top |sn| fraction)
    using C truncation-toward-zero (cu:285-287, cu:300-302, cu:316-318,
    cu:331-333).  Out-of-range indices are UB in the reference; we clamp.

    An int64 ``count`` (GLOBAL-region pair populations beyond 2^31, only
    representable under jax_enable_x64) keeps 64-bit index math; the
    fraction path then uses float64 so the truncated rank stays exact.
    """
    big = count.dtype == jnp.int64
    idt = jnp.int64 if big else jnp.int32
    count = count.astype(idt)
    if sn >= 0:
        pos = count - 1 - int(sn)
    else:
        fdt = jnp.float64 if big else jnp.float32
        cf = count.astype(fdt)
        pos = jnp.trunc(cf - 1.0 + fdt(sn) * cf).astype(idt)
    return jnp.clip(pos, 0, jnp.maximum(count - 1, 0))


def _clamp_negative(value: jax.Array) -> jax.Array:
    """Reference quirk: a relative threshold < 0 becomes -FLT_MAX (cu:288 etc.)."""
    return jnp.where(value >= 0, value, jnp.float32(-FLT_MAX))


def _local_relative_threshold(
    sims: jax.Array, mask: jax.Array, sn: float
) -> jax.Array:
    """Per-query threshold: the ``_relative_pos``-th smallest masked row
    entry, recovered exactly by MSD radix selection over the materialized
    sims (the reference's per-query ascending std::sort, cu:269-273, needs
    only ONE rank statistic — a full sort is O(M log M) work and, on TPU,
    a bitonic network; NUM_DIGITS fused compare-and-reduce passes over the
    row recover the identical element)."""
    count = mask.sum(axis=1)
    k = _relative_pos(count, sn)
    val = radix_select(
        lambda prefix, digit: masked_digit_hist(sims, mask, prefix, digit),
        k,
        count == 0,
    )
    return _clamp_negative(val)


def _global_relative_threshold(sims: jax.Array, mask: jax.Array, sn: float) -> jax.Array:
    """Scalar threshold: the ``_relative_pos``-th smallest masked entry of
    the WHOLE block (the reference's global ascending std::sort of the
    flattened pair population, cu:266-268), via the same radix selection
    with the block flattened to a single population row."""
    flat = sims.reshape(1, -1)
    fmask = mask.reshape(1, -1)
    count = fmask.sum(axis=1)
    k = _relative_pos(count, sn)
    val = radix_select(
        lambda prefix, digit: masked_digit_hist(flat, fmask, prefix, digit),
        k,
        count == 0,
    )
    return _clamp_negative(val[0])


def topk_relative_threshold(
    topk: jax.Array, counts: jax.Array, sn: float, region: "MiningRegion",
    count_dtype=jnp.int32,
) -> jax.Array:
    """RELATIVE_{HARD,EASY} threshold from per-query K-largest candidate
    buffers — the sparse-candidate fast path for the POSITIVE side.

    With identity-balanced batches each query has only
    ``img_num_per_identity*G - 1`` same-label candidates among the whole
    pool (def.prototxt:25-26 makes that 2 per identity), so when every
    query's candidate count fits a K-slot buffer, the buffer IS the
    complete per-query candidate list and the reference's ascending
    sorted-list indexing (cu:285-287 / cu:300-302) reduces to a sort of
    N x K values — no full-population selection needed.  The buffer must
    hold values bit-identical to the engine's sim computation (the
    streaming engines extract them inside the same kernel sweep that
    computes the sims), so the selected element matches the streamed
    radix selection exactly.

    Args:
      topk: [N, K] the K largest candidate sims per query, padded with
        ``-FLT_MAX``.  Finite sims only — a ``-inf`` candidate would
        sort below the padding sentinel and shift the index arithmetic.
      counts: int [N] true candidate count per query; only valid when
        ``counts.max() <= K`` (callers guard with ``lax.cond``).
      sn: the identsn/diffsn rank parameter (see ``_relative_pos``).
      region: LOCAL (per-query list, cu:285) or GLOBAL (one list over
        the whole population, cu:300).
      count_dtype: the dtype the RADIX path would rank the same
        population in (``population_count_dtype`` of the full pair
        population) — GLOBAL rank arithmetic must run in the identical
        int/float widths or the ``lax.cond`` fast/fallback branches
        could select ranks differing by one near fractional-sn
        boundaries (int64 -> float64 ``_relative_pos``, int32 ->
        float32).  LOCAL ranks are per-query int32 in both paths.

    Returns: float32 [N] thresholds (GLOBAL broadcasts one value), with
    the reference's empty -> +FLT_MAX and ``< 0 -> -FLT_MAX`` quirks.
    """
    n, kcap = topk.shape
    if region == MiningRegion.GLOBAL:
        # The buffer's n*K candidates always fit int32, but the rank
        # arithmetic mirrors the radix path's dtype (see above).
        total = counts.astype(count_dtype).sum()
        k = _relative_pos(total[None], sn)[0].astype(jnp.int32)
        total32 = total.astype(jnp.int32)  # <= n*K, always representable
        flat = jnp.sort(topk.reshape(-1))  # ascending, padding first
        pos = jnp.int32(flat.shape[0]) - total32 + k
        val = flat[jnp.clip(pos, 0, flat.shape[0] - 1)]
        val = jnp.where(total32 == 0, jnp.float32(FLT_MAX), val)
        return _clamp_negative(jnp.broadcast_to(val, (n,)))
    counts = counts.astype(jnp.int32)
    k = _relative_pos(counts, sn)
    asc = jnp.sort(topk, axis=1)  # ascending, padding first
    pos = jnp.int32(kcap) - counts + k
    val = jnp.take_along_axis(
        asc, jnp.clip(pos, 0, kcap - 1)[:, None], axis=1
    )[:, 0]
    val = jnp.where(counts == 0, jnp.float32(FLT_MAX), val)
    return _clamp_negative(val)


def mining_thresholds(
    sims: jax.Array, same: jax.Array, diff: jax.Array, cfg: NPairLossConfig
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(pos_thr[N], neg_thr[N], max_all[N]) per the reference's 8-branch grid.

    Absolute (HARD/EASY/RAND) thresholds (cu:279, cu:296, cu:310, cu:327):
      AP LOCAL  : per-query max between-class sim     (hardest negative)
      AP GLOBAL : block-wide max between-class sim
      AN LOCAL  : per-query min within-class sim      (hardest positive)
      AN GLOBAL : block-wide min within-class sim
    RELATIVE thresholds index the ascending-sorted sim lists (see
    ``_relative_pos``).  ``max_all`` is the per-query max over all non-self
    sims, used for exp stabilization (cu:229-258).
    """
    n = sims.shape[0]
    neg_fill = jnp.float32(-FLT_MAX)
    pos_fill = jnp.float32(FLT_MAX)

    max_between = jnp.where(diff, sims, neg_fill).max(axis=1)  # cu:252-255
    min_within = jnp.where(same, sims, pos_fill).min(axis=1)  # cu:242-245
    max_all = jnp.where(same | diff, sims, neg_fill).max(axis=1)  # cu:246-257

    # AP (positive-pair) threshold, cu:277-306.
    if cfg.ap_mining_region == MiningRegion.LOCAL:
        if cfg.ap_mining_method in _RELATIVE:
            pos_thr = _local_relative_threshold(sims, same, cfg.identsn)
        else:
            pos_thr = max_between
    else:  # GLOBAL
        if cfg.ap_mining_method in _RELATIVE:
            pos_thr = jnp.broadcast_to(
                _global_relative_threshold(sims, same, cfg.identsn), (n,)
            )
        else:
            pos_thr = jnp.broadcast_to(jnp.where(diff, sims, neg_fill).max(), (n,))

    # AN (negative-pair) threshold, cu:307-337.
    if cfg.an_mining_region == MiningRegion.LOCAL:
        if cfg.an_mining_method in _RELATIVE:
            neg_thr = _local_relative_threshold(sims, diff, cfg.diffsn)
        else:
            neg_thr = min_within
    else:  # GLOBAL
        if cfg.an_mining_method in _RELATIVE:
            neg_thr = jnp.broadcast_to(
                _global_relative_threshold(sims, diff, cfg.diffsn), (n,)
            )
        else:
            neg_thr = jnp.broadcast_to(jnp.where(same, sims, pos_fill).min(), (n,))

    return pos_thr, neg_thr, max_all


def streaming_supported(cfg: "NPairLossConfig") -> bool:
    """True when the mining config needs only single-pass min/max thresholds
    (absolute methods).  Both streaming engines (parallel.ring and
    ops.pallas_npair) support EVERY config — RELATIVE_* via exact radix
    selection — but a False here means the config pays 4 extra streamed
    passes over the pair tiles per relative threshold; use this as the
    cost signal, not a support gate."""
    return (
        cfg.ap_mining_method in _ABSOLUTE and cfg.an_mining_method in _ABSOLUTE
    )


def absolute_thresholds(
    min_within: jax.Array, max_between: jax.Array, cfg: "NPairLossConfig"
) -> Tuple[jax.Array, jax.Array]:
    """(pos_thr, neg_thr) from streamed per-query stats, absolute methods
    only (cu:279, 296, 310, 327).  GLOBAL region means this rank's whole
    N x (N*G) block — each rank's own extremum, no cross-rank reduction —
    so it reduces over the query axis of the streamed stats."""
    if cfg.ap_mining_region == MiningRegion.LOCAL:
        pos_thr = max_between
    else:
        pos_thr = jnp.broadcast_to(max_between.max(), max_between.shape)
    if cfg.an_mining_region == MiningRegion.LOCAL:
        neg_thr = min_within
    else:
        neg_thr = jnp.broadcast_to(min_within.min(), min_within.shape)
    return pos_thr, neg_thr


# ---------------------------------------------------------------------------
# Pair selection (reference: GetSampledPairMtx kernel, cu:69-122)
# ---------------------------------------------------------------------------


def selection_predicates(
    sims: jax.Array, pt: jax.Array, nt: jax.Array, cfg: NPairLossConfig
) -> Tuple[jax.Array, jax.Array]:
    """(pos_sel, neg_sel) comparison predicates of cu:80-119 against the
    margin-adjusted thresholds ``pt``/``nt`` (broadcastable to sims).

    The single home of the quirk-sensitive comparison directions — shared
    by the dense path, the ring path and the Pallas-blockwise kernels so
    the three can never desynchronize.
    """
    m = cfg.ap_mining_method
    if m == MiningMethod.HARD:
        pos_sel = sims < pt
    elif m == MiningMethod.EASY:
        pos_sel = sims >= pt
    elif m == MiningMethod.RAND:  # quirk: ALL (cu:88-89)
        pos_sel = jnp.ones_like(sims, dtype=bool)
    elif m == MiningMethod.RELATIVE_HARD:
        pos_sel = sims <= pt
    else:  # RELATIVE_EASY
        pos_sel = sims >= pt

    m = cfg.an_mining_method
    if m == MiningMethod.HARD:
        neg_sel = sims > nt
    elif m == MiningMethod.EASY:
        neg_sel = sims <= nt
    elif m == MiningMethod.RAND:  # quirk: ALL (cu:109-110)
        neg_sel = jnp.ones_like(sims, dtype=bool)
    elif m == MiningMethod.RELATIVE_HARD:
        neg_sel = sims >= nt
    else:  # RELATIVE_EASY
        neg_sel = sims <= nt

    return pos_sel, neg_sel


def selection_mask(
    sims: jax.Array,
    same: jax.Array,
    diff: jax.Array,
    pos_thr: jax.Array,
    neg_thr: jax.Array,
    cfg: NPairLossConfig,
) -> jax.Array:
    """0/1 per-pair selection mask; exact comparison operators of cu:80-119."""
    pt = (pos_thr + jnp.float32(cfg.margin_ident))[:, None]
    nt = (neg_thr + jnp.float32(cfg.margin_diff))[:, None]
    pos_sel, neg_sel = selection_predicates(sims, pt, nt, cfg)
    return jnp.where(same, pos_sel, jnp.where(diff, neg_sel, False))


# ---------------------------------------------------------------------------
# Forward core
# ---------------------------------------------------------------------------


def resolve_matmul_precision(precision: Optional[str]) -> jax.lax.Precision:
    """Engine-wide similarity-matmul precision knob.

    ``"highest"`` (default everywhere) keeps full fp32 on the MXU — the
    ~6-pass bf16 decomposition that bit-matches the reference's cuBLAS
    sgemm (cu:218) and the NumPy oracle.  ``"default"`` opts into the
    single-pass bf16-multiply/fp32-accumulate MXU mode: ~6x faster sim
    and backward gemms, at ~1e-3-level sim rounding — mined thresholds
    and selections then differ from the oracle near decision boundaries,
    so this is a THROUGHPUT mode, not a parity mode (training-quality
    pinned by test, bit-parity deliberately not claimed).
    """
    if precision is None:
        return jax.lax.Precision.HIGHEST
    try:
        return {
            "highest": jax.lax.Precision.HIGHEST,
            "default": jax.lax.Precision.DEFAULT,
        }[precision]
    except KeyError:
        raise ValueError(
            f"matmul_precision must be 'highest' or 'default', got "
            f"{precision!r}") from None


# Trace-time precision for the streaming engines' kernel gemms (the
# dense engine threads the string directly).  A ContextVar — not a
# module global — so concurrent traces in different threads cannot
# cross-contaminate: each engine wraps its fwd/bwd tracing in
# ``matmul_precision_ctx`` and the kernel bodies read
# ``active_matmul_precision()`` while being traced inside it.
_MATMUL_PRECISION_VAR = contextvars.ContextVar(
    "npair_matmul_precision", default=jax.lax.Precision.HIGHEST)


@contextlib.contextmanager
def matmul_precision_ctx(matmul_precision: Optional[str]):
    token = _MATMUL_PRECISION_VAR.set(
        resolve_matmul_precision(matmul_precision))
    try:
        yield
    finally:
        _MATMUL_PRECISION_VAR.reset(token)


def active_matmul_precision() -> jax.lax.Precision:
    return _MATMUL_PRECISION_VAR.get()


def _forward_core(
    features: jax.Array,
    labels: jax.Array,
    cfg: NPairLossConfig,
    axis_name: Optional[str],
    matmul_precision: Optional[str] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array], Dict[str, jax.Array]]:
    """Shared forward; returns (loss, aux-for-metrics, residuals-for-vjp)."""
    features = features.astype(jnp.float32)
    n_local = features.shape[0]

    if axis_name is None:
        total_features = features
        total_labels = labels
        rank = jnp.int32(0)
        num_shards = 1
    else:
        # MPI_Allgather of features and labels (cu:17-43) as in-graph ICI
        # collectives; rank-r block lands at rows [r*N, (r+1)*N) exactly as
        # MPI_Allgather orders recvbuf.  The nested comm/ scope is the
        # fleet observatory's exchange-path marker (obs.fleet.comms):
        # collective bytes whose op_name carries it are attributed to a
        # declared exchange path; metadata-only, the program is
        # unchanged.
        with jax.named_scope("npair/gather"), \
                jax.named_scope("comm/all_gather"):
            total_features = jax.lax.all_gather(
                features, axis_name, axis=0, tiled=True
            )
            total_labels = jax.lax.all_gather(
                labels, axis_name, axis=0, tiled=True
            )
        rank = jax.lax.axis_index(axis_name).astype(jnp.int32)
        # Trace-time import: ops must not import the parallel package at
        # module level (parallel.mesh imports this module), and the
        # axis-size API moved across jax releases (parallel/_compat).
        from npairloss_tpu.parallel._compat import axis_size

        num_shards = axis_size(axis_name)

    # Similarity matrix S = F_local @ F_total^T on the MXU (cu:218,
    # dot_normalizer = 1 in forward per cu:216).  HIGHEST (the default —
    # see resolve_matmul_precision) keeps full fp32 on the MXU; the TPU
    # default mode would truncate fp32 operands to bf16 and break
    # bit-parity with the oracle.
    with jax.named_scope("npair/sim"):
        sims = jnp.dot(
            features,
            total_features.T,
            preferred_element_type=jnp.float32,
            precision=resolve_matmul_precision(matmul_precision),
        )

    with jax.named_scope("npair/mine"):
        same, diff = pair_masks(labels, total_labels, rank, n_local)
        pos_thr, neg_thr, max_all = mining_thresholds(sims, same, diff, cfg)
    with jax.named_scope("npair/select"):
        sel = selection_mask(sims, same, diff, pos_thr, neg_thr, cfg)

    sel_pos = same & sel  # _tmp_Select_Ident, cu:355
    sel_neg = diff & sel  # _tmp_Select_Diff, cu:358
    ident_num = sel_pos.sum(axis=1).astype(jnp.float32)  # identNum, cu:357
    diff_num = sel_neg.sum(axis=1).astype(jnp.float32)  # diffNum, cu:360

    # Stabilized exponentials (Minus_Querywise_Maxval, cu:124-156).  The
    # pre-selection exp'd matrix feeds the retrieval metric (cu:132).
    # Masking must be where-based, not multiplicative: a query with no pairs
    # at all has max_all = -FLT_MAX, so sim_exp overflows to +inf and
    # inf * 0 would poison the row sums with NaN — the reference kernel
    # zeroes non-pair entries before its gemv reductions (cu:152-154).
    with jax.named_scope("npair/loss"):
        sim_exp = jnp.exp(sims - max_all[:, None])
        exp_pos = jnp.where(sel_pos, sim_exp, 0.0)  # _innerProd_temp1, cu:373
        exp_neg = jnp.where(sel_neg, sim_exp, 0.0)  # _innerProd_temp2, cu:376

        ident_sum = exp_pos.sum(axis=1)  # loss_ident_value I_q, cu:375
        all_sum = ident_sum + exp_neg.sum(axis=1)  # I_q + D_q, cu:380

        # ManipulateDIVandLOG (cu:158-171): zero-count queries contribute 0.
        valid = (ident_sum != 0) & (all_sum != 0)
        log_q = jnp.where(
            valid, jnp.log(jnp.where(valid, ident_sum / all_sum, 1.0)), 0.0
        )
        loss = -log_q.sum() / jnp.float32(n_local)  # cu:384-385

    aux = {
        "sim": sims,
        "sim_exp": sim_exp,
        "total_labels": total_labels,
        "rank": rank,
        "ident_num": ident_num,
        "diff_num": diff_num,
        "pos_threshold": pos_thr,
        "neg_threshold": neg_thr,
    }
    residuals = {
        "features": features,
        "total_features": total_features,
        "exp_pos": exp_pos,
        "exp_neg": exp_neg,
        "ident_sum": ident_sum,
        "all_sum": all_sum,
        "rank": rank,
        "num_shards": num_shards,
    }
    return loss, aux, residuals


def _reference_backward(
    res: Dict[str, Any], g: jax.Array, axis_name: Optional[str],
    matmul_precision: Optional[str] = None,
) -> jax.Array:
    """Analytic backward with the reference's exact scaling (cu:420-499).

    part1 = exp_pos / I_q,  part2 = exp_pos / (I+D)_q,  part3 = exp_neg / (I+D)_q
    (Get_Query_Diff_Part, cu:438-446, each 0-guarded per cu:412-417);
    query-role grad  = (-p1+p2+p3) @ F_total * lw/N         (cu:448-453)
    db-role grad     = (-p1+p2+p3)^T @ F_local * lw/N       (cu:455-460)
    db-role grad     = psum(db-role) / G                    (MPI_Allreduce + 1/G, cu:462-489)
    final            = 0.5 * db_role[rank*N:(rank+1)*N] + 0.5 * query_role  (cu:492-497)
    """
    features = res["features"]
    total_features = res["total_features"]
    n_local = features.shape[0]

    def _safe_div(num, den):
        ok = den != 0
        return jnp.where(ok[:, None], num / jnp.where(ok, den, 1.0)[:, None], 0.0)

    p1 = _safe_div(res["exp_pos"], res["ident_sum"])
    p2 = _safe_div(res["exp_pos"], res["all_sum"])
    p3 = _safe_div(res["exp_neg"], res["all_sum"])
    # dot_normalizer is the query count in backward (cu:427), unlike forward.
    w = (-p1 + p2 + p3) * (g / jnp.float32(n_local))

    prec = resolve_matmul_precision(matmul_precision)
    grad_query = jnp.dot(
        w,
        total_features,
        preferred_element_type=jnp.float32,
        precision=prec,
    )
    grad_db = jnp.dot(
        w.T,
        features,
        preferred_element_type=jnp.float32,
        precision=prec,
    )

    if axis_name is not None:
        # MPI_Allreduce of the database-role grads (cu:462-489); the
        # comm/ scope marks the exchange path for fleet attribution.
        with jax.named_scope("comm/allreduce"):
            grad_db = jax.lax.psum(grad_db, axis_name)
    grad_db = grad_db / jnp.float32(res["num_shards"])

    own_rows = jax.lax.dynamic_slice_in_dim(
        grad_db, res["rank"] * n_local, n_local, axis=0
    )
    return 0.5 * own_rows + 0.5 * grad_query


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _npair_core(features, labels, cfg: NPairLossConfig,
                axis_name: Optional[str], matmul_precision: Optional[str]):
    loss, aux, _ = _forward_core(
        features, labels, cfg, axis_name, matmul_precision)
    return loss, aux


def _npair_core_fwd(features, labels, cfg, axis_name, matmul_precision):
    loss, aux, res = _forward_core(
        features, labels, cfg, axis_name, matmul_precision)
    res["labels"] = labels
    return (loss, aux), res


def _npair_core_bwd(cfg, axis_name, matmul_precision, res, cotangents):
    g, _ = cotangents  # aux outputs are non-differentiable monitors
    d_features = _reference_backward(res, g, axis_name, matmul_precision)
    labels = res["labels"]
    if jnp.issubdtype(labels.dtype, jnp.floating):
        d_labels = jnp.zeros(labels.shape, labels.dtype)
    else:
        d_labels = np.zeros(labels.shape, jax.dtypes.float0)
    return d_features, d_labels


_npair_core.defvjp(_npair_core_fwd, _npair_core_bwd)


def npair_loss_with_aux(
    features: jax.Array,
    labels: jax.Array,
    cfg: NPairLossConfig = NPairLossConfig(),
    axis_name: Optional[str] = None,
    matmul_precision: Optional[str] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Multi-class N-pair loss with mining; returns (loss, aux).

    Args:
      features: [N_local, D] embedding batch of this shard (typically
        L2-normalized upstream, matching the reference's L2Normalize bottom,
        def.prototxt:115-126).
      labels: [N_local] identity labels (int or float).
      cfg: static mining/margin configuration.
      axis_name: mesh axis to all-gather the negative pool over; ``None``
        means single-shard (G = 1).
      matmul_precision: sim/backward gemm MXU precision — ``None``/
        ``"highest"`` for oracle bit-parity, ``"default"`` for the ~6x
        faster single-pass bf16 mode (``resolve_matmul_precision``).

    The returned ``aux`` feeds the retrieval metrics (``ops.metrics``); it is
    NOT differentiable — gradients flow only through the loss, mirroring the
    reference where thresholds, masks and counts are constants in backward.
    """
    if cfg.grad_mode == "reference":
        return _npair_core(features, labels, cfg, axis_name,
                           matmul_precision)
    loss, aux, _ = _forward_core(
        features,
        jax.lax.stop_gradient(labels),
        cfg,
        axis_name,
        matmul_precision,
    )
    return loss, jax.lax.stop_gradient(aux)


def npair_loss(
    features: jax.Array,
    labels: jax.Array,
    cfg: NPairLossConfig = NPairLossConfig(),
    axis_name: Optional[str] = None,
    matmul_precision: Optional[str] = None,
) -> jax.Array:
    """Scalar multi-class N-pair loss (see ``npair_loss_with_aux``)."""
    return npair_loss_with_aux(
        features, labels, cfg, axis_name, matmul_precision)[0]
