"""Shared k-means: farthest-point seeding + Lloyd's — ONE implementation.

Two consumers, one math (ISSUE 11 / ROADMAP item 2):

  * the offline clustering-quality metric (``ops.eval_retrieval``
    re-exports :func:`kmeans_assign` for the NMI protocol — identity-
    pinned by tests/test_ivf.py, so the eval numbers and the serving
    index can never drift apart);
  * the serving-side IVF index builder (``serve.ivf``), which needs the
    CENTROIDS (not just assignments) and must scale past the
    N x k distance matrix a 10^6-row gallery would materialize —
    :func:`kmeans_fit` trains on a bounded sample and
    :func:`assign_to_centroids` streams the full assignment in fixed
    row blocks (the ``gallery_recall_at_k`` trick applied to k-means).

Centroid seeding is the deterministic farthest-point traversal (the
greedy k-means++ variant): a seeded random first point, then each next
centroid is the point maximizing the min distance to those already
chosen.  A seeded-permutation init — the obvious alternative —
routinely seeds one tight cluster twice and misses another entirely,
and Lloyd's cannot escape that local optimum.  Ties break to the lowest
index, so results are deterministic for a given seed.  Empty clusters
keep their previous centroid.  Euclidean on L2-normalized embeddings ==
cosine, matching the retrieval metric and the serving score.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _sq_dists(x: jax.Array, centroids: jax.Array) -> jax.Array:
    """(N, k) squared distances via the expansion trick — no N x k x d
    intermediate."""
    return (
        jnp.sum(x * x, 1, keepdims=True)
        - 2.0 * x @ centroids.T
        + jnp.sum(centroids * centroids, 1)[None, :]
    )


@functools.partial(jax.jit, static_argnames=("k",))
def farthest_point_init(x: jax.Array, k: int, seed: int = 0) -> jax.Array:
    """Deterministic farthest-point centroid seeding; returns (k, d).

    With k > N the argmax over an all-zero min-distance vector repeats
    point 0 — duplicate centroids whose surplus clusters come out empty
    after Lloyd's (the IVF layout masks them; see serve/ivf.py).
    """
    n, d = x.shape
    x = x.astype(jnp.float32)
    first = jax.random.randint(jax.random.PRNGKey(seed), (), 0, n)
    centroids0 = jnp.zeros((k, d), jnp.float32).at[0].set(x[first])

    def pick(i, carry):
        centroids, min_sq = carry
        sq = jnp.sum((x - centroids[i - 1]) ** 2, axis=1)
        min_sq = jnp.minimum(min_sq, sq)
        nxt = jnp.argmax(min_sq)
        return centroids.at[i].set(x[nxt]), min_sq

    centroids, _ = jax.lax.fori_loop(
        1, k, pick, (centroids0, jnp.full((n,), jnp.inf, jnp.float32))
    )
    return centroids


def _lloyd_step(x: jax.Array, centroids: jax.Array) -> jax.Array:
    """One Lloyd iteration; empty clusters keep their centroid."""
    k = centroids.shape[0]
    assign = jnp.argmin(_sq_dists(x, centroids), axis=1)
    one_hot = jax.nn.one_hot(assign, k, dtype=jnp.float32)
    counts = one_hot.sum(0)
    sums = one_hot.T @ x
    return jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0),
        centroids,
    )


@functools.partial(jax.jit, static_argnames=("iters",))
def lloyd_iterate(x: jax.Array, centroids: jax.Array,
                  iters: int = 20) -> jax.Array:
    """``iters`` Lloyd refinement steps on fixed data; returns (k, d)."""
    x = x.astype(jnp.float32)

    def step(c, _):
        return _lloyd_step(x, c), None

    centroids, _ = jax.lax.scan(step, centroids, None, length=iters)
    return centroids


@functools.partial(jax.jit, static_argnames=("k", "iters"))
def kmeans_assign(
    embeddings: jax.Array,
    k: int,
    iters: int = 20,
    seed: int = 0,
) -> jax.Array:
    """Lloyd's k-means on-device; returns the (N,) cluster assignment.

    The offline clustering-quality entry point (NMI protocol,
    ``ops.eval_retrieval``): farthest-point init + ``iters`` Lloyd
    steps + final argmin, all over the FULL point set — fine at eval
    sizes, quadratic-memory at gallery scale (the IVF builder uses
    :func:`kmeans_fit` + :func:`assign_to_centroids` instead, same
    seeding and refinement math).
    """
    x = embeddings.astype(jnp.float32)
    centroids = farthest_point_init(x, k, seed)
    centroids = lloyd_iterate(x, centroids, iters)
    return jnp.argmin(_sq_dists(x, centroids), axis=1)


@functools.partial(jax.jit, static_argnames=("block",))
def _assign_blocks(x: jax.Array, centroids: jax.Array,
                   block: int) -> jax.Array:
    """Streamed nearest-centroid assignment: row blocks through one
    ``lax.map``, so the N x k distance matrix is never materialized.
    The final clamped block overlaps an earlier one; overwrite
    semantics deduplicate exactly (duplicated rows carry identical
    assignments) — the ``gallery_recall_at_k`` pattern."""
    n = x.shape[0]
    b = int(min(block, n))
    n_blocks = -(-n // b)
    x = x.astype(jnp.float32)
    c = centroids.astype(jnp.float32)

    def one_block(start):
        q = jax.lax.dynamic_slice_in_dim(x, start, b, axis=0)
        a = jnp.argmin(_sq_dists(q, c), axis=1).astype(jnp.int32)
        return start + jnp.arange(b, dtype=jnp.int32), a

    starts = jnp.minimum(
        jnp.arange(n_blocks, dtype=jnp.int32) * b, max(n - b, 0)
    )
    rows, assign = jax.lax.map(one_block, starts)
    out = jnp.zeros((n,), jnp.int32)
    return out.at[rows.reshape(-1)].set(assign.reshape(-1))


def assign_to_centroids(
    embeddings: np.ndarray,
    centroids: np.ndarray,
    block: int = 65536,
) -> np.ndarray:
    """Host-side full-set assignment against fixed centroids, streamed
    in ``block``-row slabs; numpy in, (N,) int32 out."""
    return np.asarray(_assign_blocks(
        jnp.asarray(np.asarray(embeddings, np.float32)),
        jnp.asarray(np.asarray(centroids, np.float32)),
        block,
    ))


def kmeans_fit(
    embeddings: np.ndarray,
    k: int,
    iters: int = 20,
    seed: int = 0,
    train_size: Optional[int] = None,
    block: int = 65536,
) -> np.ndarray:
    """Fit centroids at gallery scale; returns host (k, d) float32.

    Farthest-point seeding + Lloyd refinement run on a seeded
    ``train_size``-row subsample when the set is larger (k-means
    centroid QUALITY saturates well below gallery size, while the
    init's k x N distance sweep does not) — the full set only pays the
    streamed :func:`assign_to_centroids` pass, which the IVF builder
    does anyway.  ``k`` is clamped to the training-set size.
    """
    x = np.asarray(embeddings, np.float32)
    n = x.shape[0]
    if n == 0:
        raise ValueError("cannot fit k-means on an empty set")
    train = x
    if train_size is not None and n > int(train_size):
        sel = np.random.default_rng(seed).choice(
            n, size=int(train_size), replace=False)
        sel.sort()
        train = x[sel]
    k = int(min(k, train.shape[0]))
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    xd = jnp.asarray(train)
    centroids = farthest_point_init(xd, k, seed)
    centroids = lloyd_iterate(xd, centroids, iters)
    return np.asarray(centroids)
