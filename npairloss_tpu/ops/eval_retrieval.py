"""Offline full-gallery retrieval evaluation (the deployment protocol).

The reference's in-training ``retrieve_top*`` metrics are within-batch
(npair_multi_class_loss.cu:173-206) — fine as a training monitor, but
the numbers metric-learning papers report for the reference's target
datasets (CUB-200-2011 / Stanford Online Products; Sohn, NIPS 2016) are
full-gallery: every test image queries the ENTIRE test set.  This module
is that protocol, computed on-device from extracted embeddings (the
``python -m npairloss_tpu extract`` output):

    Recall@K = fraction of queries whose K nearest gallery neighbors
    (cosine similarity, self excluded) contain a same-class item.

Scales past HBM-square limits the same way the loss engines do: queries
stream in fixed-size blocks through one jitted ``lax.map``, each block
doing an (B x N) fp32-HIGHEST matmul on the MXU + ``lax.top_k`` — the
N x N similarity matrix is never materialized.

Note the deliberate semantic difference from ``ops.metrics.recall_at_k``:
that function reproduces the reference's in-training quirks (exp'd sims,
strictly-greater-than-threshold, ties dropped) for parity; this one is
the standard membership-in-top-K protocol used for reporting.
"""

from __future__ import annotations

import functools
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_NEG_FILL = float(-np.finfo(np.float32).max)


@functools.partial(
    jax.jit, static_argnames=("ks", "query_block", "normalize")
)
def gallery_recall_at_k(
    embeddings: jax.Array,
    labels: jax.Array,
    ks: Sequence[int] = (1, 2, 4, 8, 16, 32),
    query_block: int = 1024,
    normalize: bool = True,
) -> Dict[str, jax.Array]:
    """Full-gallery Recall@K over one embedding set (queries == gallery).

    ``embeddings``: (N, D) float array (any float dtype; cosine similarity
    is computed in fp32 on the MXU).  ``labels``: (N,) int or float class
    ids.  ``normalize=False`` skips the L2 normalization when the
    embeddings are already unit-norm (the extract output is).

    Returns {"recall_at_{k}": scalar} for each k (ks exceeding N-1 are
    clamped to N-1: with the self excluded a query only has N-1
    neighbors).
    """
    n, _ = embeddings.shape
    emb = embeddings.astype(jnp.float32)
    if normalize:
        emb = emb / jnp.maximum(
            jnp.linalg.norm(emb, axis=1, keepdims=True), 1e-12
        )
    ks = tuple(int(min(k, n - 1)) for k in ks)
    max_k = max(ks)
    b = int(min(query_block, n))
    n_blocks = -(-n // b)

    def one_block(start):
        q = jax.lax.dynamic_slice_in_dim(emb, start, b, axis=0)
        sims = jnp.dot(
            q, emb.T,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        rows = start + jnp.arange(b, dtype=jnp.int32)
        cols = jnp.arange(n, dtype=jnp.int32)[None, :]
        not_self = cols != rows[:, None]
        masked = jnp.where(not_self, sims, jnp.float32(_NEG_FILL))
        _, top_idx = jax.lax.top_k(masked, max_k)
        top_same = labels[top_idx] == labels[rows][:, None]
        # hits[:, j] == some same-label item within the top (j+1)
        hits = jnp.cumsum(top_same.astype(jnp.int32), axis=1) > 0
        return rows, hits

    # dynamic_slice clamps the final block's start so every slice is
    # full-size; overlapping rows are deduplicated by weighting each
    # global row once.
    starts = jnp.minimum(
        jnp.arange(n_blocks, dtype=jnp.int32) * b, max(n - b, 0)
    )
    rows, hits = jax.lax.map(one_block, starts)
    rows = rows.reshape(-1)
    hits = hits.reshape(-1, max_k)
    # Scatter per-row hits into a dense (n, max_k) table: only the last
    # block can overlap an earlier one, and a duplicated row carries
    # identical hits, so overwrite semantics deduplicate exactly.
    table = jnp.zeros((n, max_k), dtype=bool).at[rows].set(hits)
    out = {}
    for k in ks:
        out[f"recall_at_{k}"] = table[:, k - 1].astype(jnp.float32).mean()
    return out


def evaluate_embeddings(
    embeddings: np.ndarray,
    labels: np.ndarray,
    ks: Sequence[int] = (1, 2, 4, 8, 16, 32),
    query_block: int = 1024,
) -> Dict[str, float]:
    """Host-side convenience wrapper: numpy in, python floats out."""
    out = gallery_recall_at_k(
        jnp.asarray(embeddings), jnp.asarray(labels),
        ks=tuple(ks), query_block=query_block,
    )
    return {k: float(v) for k, v in out.items()}


# -- clustering quality (the other half of the paper protocol) --------------
#
# CUB/SOP papers report NMI alongside Recall@K: k-means over the test
# embeddings (k = number of classes), then normalized mutual information
# between cluster assignments and ground-truth labels.  The k-means
# itself (farthest-point seeding + Lloyd's) lives in ``ops.kmeans`` —
# ONE implementation shared with the serving-side IVF index builder
# (serve/ivf.py), re-exported here so the eval protocol's entry point
# stays where the papers' metric is computed.

from npairloss_tpu.ops.kmeans import kmeans_assign  # noqa: F401 — shared impl


def nmi(labels_a: np.ndarray, labels_b: np.ndarray) -> float:
    """Normalized mutual information, arithmetic normalization
    2*I/(H_a + H_b) (sklearn's default ``average_method='arithmetic'``).

    Host-side numpy: the contingency table is tiny (clusters x classes)
    next to the embedding compute.
    """
    a = np.unique(np.asarray(labels_a), return_inverse=True)[1]
    b = np.unique(np.asarray(labels_b), return_inverse=True)[1]
    n = a.shape[0]
    ka, kb = a.max() + 1, b.max() + 1
    cont = np.zeros((ka, kb), np.float64)
    np.add.at(cont, (a, b), 1.0)
    pij = cont / n
    pa = pij.sum(1)
    pb = pij.sum(0)
    nz = pij > 0
    mi = float(np.sum(
        pij[nz] * np.log(pij[nz] / np.outer(pa, pb)[nz])
    ))
    ent = lambda p: float(-np.sum(p[p > 0] * np.log(p[p > 0])))
    denom = ent(pa) + ent(pb)
    if denom == 0.0:
        return 1.0  # both partitions trivial (single cluster == single class)
    return max(0.0, min(1.0, 2.0 * mi / denom))


def clustering_nmi(
    embeddings: np.ndarray,
    labels: np.ndarray,
    k: int = 0,
    iters: int = 20,
    seed: int = 0,
) -> float:
    """NMI(k-means(embeddings), labels); k defaults to #classes."""
    emb = np.asarray(embeddings, np.float32)
    emb = emb / np.maximum(
        np.linalg.norm(emb, axis=1, keepdims=True), 1e-12
    )
    k = int(k) or int(np.unique(labels).shape[0])
    assign = np.asarray(kmeans_assign(jnp.asarray(emb), k, iters, seed))
    return nmi(assign, labels)
