"""Fused Pallas IVF probe kernel — gather + score + running top-k in
one VMEM pass (ROADMAP item 3's serving half).

The scan baseline (``serve/engine.py:_ivf_probe_topk``) is a
multi-dispatch pipeline: centroid gemm -> ``lax.top_k`` probe pick ->
a ``lax.scan`` of per-probe gather+score -> a running top-k merge.
Each probe step round-trips its ``(B, cap, D)`` gathered slab through
HBM, and the int8 mode is WORSE than fp32 on XLA CPU (~13x, measured
for the ``ivf_qps_1m`` row) because the scalarized gather-then-cast
never reaches an MXU-shaped program.

This module generalizes the ``pallas_npair`` sim-cache running-top-k
(``_accum_topk``) and the ``pallas_stem`` custom-kernel idioms to the
serving path:

  * the probe set still comes from one small centroid gemm + ``top_k``
    (stage 1 — identical XLA ops to the scan baseline, so the probe
    SET is bit-identical);
  * stage 2 is ONE Pallas kernel over grid ``(B, C)``: the probed
    cluster id rides a scalar-prefetch operand, so the pipeline DMA
    fetches exactly the ``(cap, D)`` cluster tile each step needs
    (gather-by-index-map — the TPU-v4 embedding-lookup pattern), the
    MXU scores it against the query row in the configured dtype, and a
    duplicate-safe extract-max merge maintains the running ``(1, kl)``
    best in VMEM — the gathered slab never touches HBM;
  * the int8 variant reads the per-cluster scale from SMEM and dequants
    the tile's PRODUCT inside the kernel (cast-to-bf16 gemm x scalar
    scale — the exact arithmetic of the scan baseline, now MXU-shaped).

Dispatch count for the probe path drops from 4 pipeline stages to 2
(declared in :data:`PROBE_IMPLS`, stamped into bench records).

Parity contract (tests/test_pallas_ivf.py, ci.sh interpret smoke):
scores match the scan baseline to 1e-6 and recall@{1,10} vs the
brute-force oracle is identical across fp32/bf16/int8, including
ragged tails, empty/padded clusters, and ``probes > n_clusters`` —
exercised in interpret mode on CPU, so tier-1 proves the kernel
without hardware.

Like every Pallas module here: interpret mode off-TPU by default, so
the same code path runs under CPU tests and Mosaic-compiles on TPU.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# The probe-impl registry — the single source of truth the CLI flag
# vocabulary (cli._PROBE_IMPL_CHOICES), bench rows, and tests enumerate
# from (pinned by the staticcheck ``vocab`` pass, the _PRECISION_CHOICES
# pattern).  ``dispatch_count`` is the declared number of device
# pipeline stages on the probe path (centroid-select / gather / score /
# merge for the scan; centroid-select / fused kernel for the Pallas
# path) — stamped into bench records so the fused win is auditable.
PROBE_IMPLS = {
    "scan": {"dispatch_count": 4, "pallas": False},
    "fused": {"dispatch_count": 2, "pallas": True},
    "auto": {"dispatch_count": 0, "pallas": False},
}

_NEG_FILL = float(-np.finfo(np.float32).max)

_LANES = 128
# Min sublane tile per scoring dtype (pallas guide: fp32 (8,128),
# bf16 (16,128), int8 (32,128)); ``serve.ivf`` pads every packed slab's
# cap to the lcm (32) at placement time so the per-dispatch re-pad
# below is a no-op at production geometry.
_SUBLANES = {"fp32": 8, "bf16": 16, "int8": 32}
CAP_ALIGN = 32


def _default_interpret() -> bool:
    """Interpret everywhere but real TPU (the pallas_stem idiom)."""
    return jax.default_backend() != "tpu"


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


def resolve_probe_impl(impl: str, platform: Optional[str] = None) -> str:
    """``auto`` -> the per-platform pick: the fused kernel where Mosaic
    compiles it (TPU), the scan baseline elsewhere (interpret-mode
    emulation is a parity harness, not a serving path) — mirroring how
    the bench rows pick the int8/bf16 scoring dtype per platform."""
    if impl not in PROBE_IMPLS:
        raise ValueError(
            f"probe_impl must be one of {sorted(PROBE_IMPLS)}, "
            f"got {impl!r}")
    if impl != "auto":
        return impl
    platform = platform or jax.default_backend()
    return "fused" if platform == "tpu" else "scan"


def probe_dispatch_count(impl: str,
                         platform: Optional[str] = None) -> int:
    """The declared probe-path dispatch count for a (resolved) impl."""
    return PROBE_IMPLS[resolve_probe_impl(impl, platform)][
        "dispatch_count"]


def _probe_kernel(lids_ref, oks_ref, *rest, c: int, kl: int,
                  kl_pad: int, cap_pad: int, scoring: str):
    """One (query b, probe j) grid step: score the prefetched cluster
    tile and merge it into the revisited running top-kl buffer.

    ``rest`` is (scale_ref?, q_ref, tile_ref, rows_ref, out_s_ref,
    out_r_ref): the int8 per-cluster scale table travels as a third
    scalar-prefetch operand; fp32/bf16 omit it.
    """
    if scoring == "int8":
        scale_ref, q_ref, tile_ref, rows_ref, out_s_ref, out_r_ref = rest
    else:
        scale_ref = None
        q_ref, tile_ref, rows_ref, out_s_ref, out_r_ref = rest
    b, j = pl.program_id(0), pl.program_id(1)
    neg = jnp.float32(_NEG_FILL)

    @pl.when(j == 0)
    def _():
        out_s_ref[:] = jnp.full((1, kl_pad), neg, jnp.float32)
        out_r_ref[:] = jnp.zeros((1, kl_pad), jnp.int32)

    flat = b * c + j
    ok = oks_ref[flat] > 0
    g = tile_ref[0]    # (cap_pad, d_pad) in the scoring dtype
    qv = q_ref[:]      # (1, d_pad) float32
    # The scoring gemm — same arithmetic as the scan baseline's einsum,
    # fp32-accumulated on the MXU; int8 dequants INSIDE the kernel:
    # bf16-cast gemm (+-127 is bf16-exact) x the per-cluster scale
    # scalar read from SMEM.
    if scoring == "fp32":
        sims = jax.lax.dot_general(
            qv, g, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    else:
        sims = jax.lax.dot_general(
            qv.astype(jnp.bfloat16), g.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if scale_ref is not None:
            sims = sims * scale_ref[lids_ref[flat]]
    rvals = rows_ref[:]  # (1, cap_pad) int32, -1 = pad
    vals = jnp.where((rvals >= 0) & ok, sims, neg)
    # Merge candidates in [running buffer, tile-ascending] order and
    # extract the kl largest by repeated (max, remove-ONE-occurrence)
    # — the pallas_npair ``_accum_topk`` loop, extended to carry row
    # ids.  Lowest-index-wins among equals keeps ``lax.top_k``'s
    # tie-break: the running best beats an equal tile candidate and
    # lower cluster positions beat higher, exactly like the baseline's
    # best-first concat.
    work_v = jnp.concatenate([out_s_ref[:], vals], axis=1)
    work_r = jnp.concatenate([out_r_ref[:], rvals], axis=1)
    w = kl_pad + cap_pad
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
    imin = jnp.int32(np.iinfo(np.int32).min)
    new_s, new_r = [], []
    for _t in range(kl):
        mx = work_v.max(axis=1, keepdims=True)
        mi = jnp.where(work_v == mx, iota, jnp.int32(w)).min(
            axis=1, keepdims=True)
        rr = jnp.where(iota == mi, work_r, imin).max(
            axis=1, keepdims=True)
        work_v = jnp.where(iota == mi, neg, work_v)
        new_s.append(mx)
        new_r.append(rr)
    pad = kl_pad - kl
    if pad:
        new_s.append(jnp.full((1, pad), neg))
        new_r.append(jnp.zeros((1, pad), jnp.int32))
    out_s_ref[:] = jnp.concatenate(new_s, axis=1)
    out_r_ref[:] = jnp.concatenate(new_r, axis=1)


def fused_probe_topk(q, packed, rows, centroids, cvalid, scale=None, *,
                     k: int, probes: int, scoring: str, g0,
                     interpret: Optional[bool] = None
                     ) -> Tuple[jax.Array, jax.Array]:
    """Drop-in fused twin of ``engine._ivf_probe_topk``: same operands,
    same ``(B, kl)`` scores + GLOBAL gallery rows, same probe set and
    masking semantics — the gather/score/merge scan replaced by one
    Pallas kernel.  ``g0`` may be traced (the shard_map per-shard
    offset)."""
    kc_full = centroids.shape[0]
    kc_local, cap, d = packed.shape
    c = min(int(probes), kc_full)
    kl = min(int(k), c * cap)
    bq = q.shape[0]
    if interpret is None:
        interpret = _default_interpret()

    with jax.named_scope("serve/probe"):
        # Stage 1 — identical XLA ops to the scan baseline, so the
        # probe SET is bit-identical: one small (B, KC) gemm, invalid
        # centroids masked, top-C pick.
        cs = jnp.dot(
            q, centroids.T,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        cs = jnp.where(cvalid[None, :], cs, jnp.float32(_NEG_FILL))
        _, probe = jax.lax.top_k(cs, c)  # (B, c) global cluster ids
        owned = (probe >= g0) & (probe < g0 + kc_local)
        lids = jnp.where(owned, probe - g0, 0).astype(jnp.int32)

    # Tile-align the operands for the kernel's block shapes.  At
    # production geometry (D a lane multiple, cap pre-padded to
    # CAP_ALIGN by IVFIndex._place) every pad below is width zero — no
    # per-dispatch copy of the slab.
    sub = _SUBLANES[scoring]
    cap_pad = _round_up(cap, sub)
    d_pad = _round_up(d, _LANES)
    kl_pad = _round_up(kl, _LANES)
    if cap_pad != cap or d_pad != d:
        packed = jnp.pad(
            packed, ((0, 0), (0, cap_pad - cap), (0, d_pad - d)))
    if cap_pad != cap:
        rows = jnp.pad(rows, ((0, 0), (0, cap_pad - cap)),
                       constant_values=-1)
    qp = jnp.pad(q, ((0, 0), (0, d_pad - d))) if d_pad != d else q

    with_scale = scoring == "int8" and scale is not None
    n_prefetch = 3 if with_scale else 2
    kernel = functools.partial(
        _probe_kernel, c=c, kl=kl, kl_pad=kl_pad, cap_pad=cap_pad,
        scoring=scoring if with_scale or scoring != "int8" else "bf16")
    # Index maps see the scalar-prefetch refs after the grid indices:
    # the probed cluster id IS the block index — the in-kernel gather.
    tile_idx = (lambda b, j, lids_r, *_p: (lids_r[b * c + j], 0, 0))
    rows_idx = (lambda b, j, lids_r, *_p: (lids_r[b * c + j], 0))
    q_idx = (lambda b, j, *_p: (b, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(bq, c),  # b outer, j inner: outputs revisit consecutively
        in_specs=[
            pl.BlockSpec((1, d_pad), q_idx),
            pl.BlockSpec((1, cap_pad, d_pad), tile_idx),
            pl.BlockSpec((1, cap_pad), rows_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, kl_pad), q_idx),
            pl.BlockSpec((1, kl_pad), q_idx),
        ],
    )
    args = [lids.reshape(-1), owned.astype(jnp.int32).reshape(-1)]
    if with_scale:
        args.append(scale.astype(jnp.float32))
    args += [qp, packed, rows]
    with jax.named_scope("serve/probe_fused"):
        s, r = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((bq, kl_pad), jnp.float32),
                jax.ShapeDtypeStruct((bq, kl_pad), jnp.int32),
            ],
            interpret=interpret,
        )(*args)
    return s[:, :kl], r[:, :kl]
