"""Exact streamed rank selection (k-th smallest) over pair populations.

The reference computes RELATIVE_* mining thresholds by sorting the full
pair-similarity population on the host (reference:
npair_multi_class_loss.cu:266-273) and indexing the sorted list
(cu:285-287 etc.).  For streamed paths that never materialize the pair
matrix (parallel.ring, ops.pallas_npair), the same element is recovered
EXACTLY — bit pattern and all — by MSD radix selection over a monotone
float32 -> uint32 key: ``NUM_DIGITS`` rounds, each histogramming one
``RADIX_BITS``-bit digit of the candidates matching the prefix so far,
narrow k to a single bit pattern.  Each round costs one pass over the
(recomputed) pair tiles; no sort, no materialization, O(N x RADIX_BINS)
state.

The digit width is a pure VPU trade: each halving of RADIX_BITS doubles
the number of passes but shrinks the per-pass histogram work by the
same factor AND keeps it as a compare-and-reduce XLA fuses into the
row reduction (a 256-bin histogram needs either a scatter/bincount —
serialized on TPU — or 256 whole-tile compares; 16 bins need 16).  At
4 bits the histogram adds ~16 ops/pair/pass, far below the sim-tile
matmul it rides on.

This is SURVEY.md §7's "distributed top-k" growth path for GLOBAL
RELATIVE mining beyond gather-able pool sizes; the dense engine reuses
the same machinery over its materialized pair matrix in place of a full
sort (one rank statistic never needs O(E log E) work).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

FLT_MAX = float(np.finfo(np.float32).max)

# 4-bit digits: 8 passes x 16-bin compare-and-reduce histograms.
RADIX_BITS = 4
RADIX_BINS = 1 << RADIX_BITS
NUM_DIGITS = 32 // RADIX_BITS

# hist_fn(prefix: uint32[N], digit: int) -> int32[N, RADIX_BINS]: counts
# of the digit values of candidates whose higher digits equal prefix.
# For a GLOBAL (population-wide) rank the caller's hist_fn sums counts
# over queries and broadcasts, so every row narrows identically.
HistFn = Callable[[jax.Array, int], jax.Array]


def sortable_key(v: jax.Array) -> jax.Array:
    """Monotone float32 -> uint32 bit-key (the radix-sort float trick):
    key order == value order, so rank selection runs on integer digits
    and recovers the target element's exact bit pattern."""
    u = jax.lax.bitcast_convert_type(v.astype(jnp.float32), jnp.uint32)
    sign = (u & jnp.uint32(0x80000000)) != 0
    return jnp.where(sign, ~u, u | jnp.uint32(0x80000000))


def key_to_float(key: jax.Array) -> jax.Array:
    sign = (key & jnp.uint32(0x80000000)) != 0
    u = jnp.where(sign, key ^ jnp.uint32(0x80000000), ~key)
    return jax.lax.bitcast_convert_type(u, jnp.float32)


def radix_begin(k: jax.Array):
    """(k, prefix) state for a stepwise NUM_DIGITS-round MSD selection.

    The stepwise API lets callers drive SEVERAL selections through one
    shared data pass per digit (the pair tiles are the expensive part —
    one sim-tile sweep can feed both the AP and the AN histogram) and
    source the digit-0 histogram from an earlier pass (digit 0 needs no
    prefix, so the mining-stats sweep can produce it for free).
    """
    idt = jnp.int64 if k.dtype == jnp.int64 else jnp.int32
    return k.astype(idt), jnp.zeros(k.shape, jnp.uint32)


def radix_update(state, hist: jax.Array):
    """Consume one digit histogram; narrow (k, prefix) by RADIX_BITS bits."""
    k, prefix = state
    idt = k.dtype
    cum = jnp.cumsum(hist.astype(idt), axis=1)
    # First digit bin whose cumulative count exceeds k.
    b = jnp.minimum((cum <= k[:, None]).sum(axis=1), RADIX_BINS - 1)
    below = jnp.where(
        b > 0,
        jnp.take_along_axis(
            cum, jnp.maximum(b - 1, 0)[:, None], axis=1
        )[:, 0],
        jnp.asarray(0, idt),
    )
    return k - below, (prefix << jnp.uint32(RADIX_BITS)) | b.astype(jnp.uint32)


def radix_finish(state, empty: jax.Array) -> jax.Array:
    """Selected value after NUM_DIGITS updates; empty rows yield +FLT_MAX."""
    _, prefix = state
    return jnp.where(empty, jnp.float32(FLT_MAX), key_to_float(prefix))


def radix_select(hist_fn: HistFn, k: jax.Array, empty: jax.Array) -> jax.Array:
    """Value of the k-th smallest candidate per query (0-based), exact.

    Args:
      hist_fn: digit histogram oracle over the streamed population.  Its
        count dtype must match ``k``'s: int32 for populations below
        2^31, int64 (requires jax_enable_x64) beyond — int32 cumulative
        counts would wrap negative and silently select the wrong rank.
      k: int [N] target rank per query (pre-clipped to [0, count-1]).
      empty: bool [N]; rows with no candidates yield +FLT_MAX — the
        dense path's +FLT_MAX-padded sort yields FLT_MAX at any index.
    """
    state = radix_begin(k)
    for digit in range(NUM_DIGITS):
        state = radix_update(state, hist_fn(state[1], digit))
    return radix_finish(state, empty)


def population_count_dtype(max_population: int):
    """Count dtype for a (statically bounded) pair population.

    GLOBAL-region rank targets sum per-query pair counts over the whole
    block — up to N x M pairs — so int32 wraps negative beyond 2^31 and
    radix selection would silently pick the wrong element.  Raises
    loudly when 64-bit counts are needed but jax_enable_x64 is off.
    """
    if max_population <= 2**31 - 1:
        return jnp.int32
    if not jax.config.jax_enable_x64:
        raise NotImplementedError(
            f"GLOBAL RELATIVE_* mining over a pair population of up to "
            f"{max_population} (> 2^31 - 1) needs 64-bit streamed counts; "
            "enable jax_enable_x64"
        )
    return jnp.int64


def digit_of(key: jax.Array, digit: int) -> jax.Array:
    """Digit ``digit`` (0 = MSB) of a uint32 key, as int32."""
    shift = 32 - RADIX_BITS * (digit + 1)
    return (
        (key >> jnp.uint32(shift)) & jnp.uint32(RADIX_BINS - 1)
    ).astype(jnp.int32)


def prefix_matches(key: jax.Array, prefix: jax.Array, digit: int) -> jax.Array:
    """True where key's digits above ``digit`` equal ``prefix`` (always
    True for digit 0)."""
    if digit == 0:
        return jnp.ones(key.shape, bool)
    shift = 32 - RADIX_BITS * digit
    return (key >> jnp.uint32(shift)) == prefix


def masked_digit_hist(
    sims: jax.Array, mask: jax.Array, prefix: jax.Array, digit: int
) -> jax.Array:
    """int32 [N, RADIX_BINS] histogram of digit values over one masked
    tile; prefix-mismatched and unmasked entries are dropped.

    Bincount/scatter-free: one broadcast compare per bin, which XLA
    fuses straight into the row reduction (no [N, M, BINS] intermediate
    ever materializes) — TPU scatters serialize, a 16-way compare
    vectorizes.
    """
    key = sortable_key(sims)
    m = mask & prefix_matches(key, prefix[:, None], digit)
    d = jnp.where(m, digit_of(key, digit), RADIX_BINS)
    bins = jnp.arange(RADIX_BINS, dtype=jnp.int32)
    return (d[:, :, None] == bins).sum(axis=1, dtype=jnp.int32)
