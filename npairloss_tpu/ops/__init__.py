from npairloss_tpu.ops.npair_loss import (
    MiningMethod,
    MiningRegion,
    NPairLossConfig,
    npair_loss,
    npair_loss_with_aux,
)
from npairloss_tpu.ops.metrics import feature_asum, recall_at_k, retrieval_metrics
from npairloss_tpu.ops.normalize import l2_normalize
