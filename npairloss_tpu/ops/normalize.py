"""L2 normalization op.

The reference net L2-normalizes the pool5 embedding immediately before the
loss (usage/def.prototxt:115-120, layer type "L2Normalize" from the implied
Caffe fork).  On TPU this is a fused rsqrt-scale that XLA folds into the
surrounding graph.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def l2_normalize(x: jax.Array, axis: int = -1, eps: float = 1e-12) -> jax.Array:
    """x / ||x||_2 along ``axis``, numerically guarded.

    Computed in float32 then cast back, so bf16 activations keep unit norm.
    """
    xf = x.astype(jnp.float32)
    sq = jnp.sum(xf * xf, axis=axis, keepdims=True)
    out = xf * jax.lax.rsqrt(jnp.maximum(sq, eps))
    return out.astype(x.dtype)
