"""Named failpoints — deterministic fault injection for resilience tests.

A failpoint is a named site in the codebase where a fault *may* be
injected: the call site asks ``should_fire(name)`` (or ``fire(name)``,
which raises) and the registry answers based on what tests or the
environment armed.  Production runs pay one dict lookup per site; an
unarmed registry never fires.

Arming, two ways:

  * programmatic (tests): ``arm(name, times=N)`` / ``disarm(name)``, or
    the ``armed(name, times=N)`` context manager;
  * environment (CLI smoke runs): ``NPAIRLOSS_FAILPOINTS`` holds a
    comma-separated ``name[:count[@delay]]`` list, e.g.
    ``NPAIRLOSS_FAILPOINTS="snapshot.save.io:2,data.worker"`` — parsed
    once at first use.  ``@delay`` skips the site's first ``delay``
    checks before the ``count`` fires begin
    (``train.collapse:160@60`` = 60 healthy steps, then 160 collapsed
    ones) — faults that must start MID-run, after snapshots/warmup
    exist, are armed this way instead of with wall-clock sleeps.

Failpoints wired into the framework (docs/RESILIENCE.md):

  ==========================  =============================================
  ``snapshot.save.io``        transient OSError inside the snapshot write
                              (exercises the retry/backoff path)
  ``snapshot.restore.io``     transient OSError inside snapshot restore
  ``snapshot.commit.torn``    commit a snapshot whose manifest checksums
                              are wrong — a "torn"/corrupt snapshot the
                              resume validator must detect and skip
  ``snapshot.commit.crash``   die after the array write but before the
                              atomic rename (leaves only a tmp dir that
                              resume must never see)
  ``data.worker``             crash the data prefetch worker (exercises
                              bounded respawn)
  ``index.commit.crash``      die inside GalleryIndex.save's atomic
                              commit, after the previous index is
                              renamed aside but before the new one
                              lands (loaders must see old-or-new,
                              never a torn mix)
  ``pipeline.stage``          crash the pipelined loop's device staging
                              thread (exercises clean prefetcher drain +
                              resume, docs/PIPELINE.md)
  ``step.nan_loss``           replace the step's loss with NaN (exercises
                              the divergence guard; in the pipelined loop
                              the poison lands in the metric window at
                              the next boundary read)
  ``serve.latency``           sleep ``SERVE_LATENCY_FAULT_S`` inside the
                              serving dispatch (after warmup's path, so
                              warmed compiles stay fast) — deterministic
                              p99 spikes for driving the live-obs alert
                              lifecycle (docs/OBSERVABILITY.md §Live)
  ``serve.queue_stall``       stall the micro-batcher's dispatcher thread
                              before it drains the queue, so admissions
                              pile up — drives the queue-saturation
                              watchdog and the backpressure path
  ``serve.replica_crash``     kill one serving replica mid-dispatch
                              (serve/replicas.py): its in-flight batch
                              and queued batches REROUTE to a surviving
                              replica (zero client-visible errors), the
                              router stops selecting it, and the
                              remaining replicas absorb the load — the
                              front end's answered+errors+rejected
                              invariant must hold through the crash;
                              supports ``@delay`` arming so the crash
                              lands mid-window (docs/RESILIENCE.md
                              §Gameday)
  ``serve.stale_model``       add ``STALE_AGE_FAULT_S`` to the model age
                              the serving freshness probe publishes —
                              the model-staleness alert fires without
                              waiting real hours, driving the snapshot
                              hot-swap remediation (docs/RESILIENCE.md
                              §Remediation)
  ``serve.compile_storm``     count one PHANTOM post-warmup compile in
                              the query engine's compile accounting
                              (no real XLA compile happens) — drives
                              the post-warmup-compile watchdog and the
                              re-warm remediation; under the strict
                              compile guard it raises like a real one
  ``train.collapse``          force ``an_threshold_mean`` to 1.0 in the
                              emitted train row (telemetry/display see
                              a collapsing embedding space, the actual
                              state is untouched) — drives the
                              embedding-collapse watchdog and the
                              trainer-rollback remediation
  ``serve.recall_drop``       deterministically mis-probe the IVF top-C
                              selection for one warmed dispatch (the
                              centroid scan runs against the negated
                              query — worst clusters probed, recall
                              collapses, shapes/compile signatures
                              unchanged); supports ``name:count@delay``
                              arming like every failpoint — drives the
                              recall-floor watchdog and the
                              probe-escalation remediation
                              (docs/OBSERVABILITY.md §Quality)
  ``snapshot.commit.dirsync``  die after the atomic rename but before
                              the parent-directory fsync — the commit
                              landed in the page cache only, the
                              durability hole the dir-fsync exists to
                              close (docs/RESILIENCE.md §Durability)
  ``wal.append.torn``         truncate the WAL record mid-write (half
                              the framed bytes land) — recovery must
                              truncate the torn tail loudly and count
                              it, never replay garbage
  ``wal.rotate.crash``        die during segment rotation, after the
                              old segment's seal is written but before
                              the new segment file exists — recovery
                              must start a fresh segment
  ``wal.gc.crash``            die mid-GC, after some covered segments
                              are unlinked but not all — recovery must
                              tolerate the gap and replay is unaffected
                              (GC only ever removes sealed segments at
                              or below the checkpoint watermark)
  ==========================  =============================================

``times`` counts fires: an armed point fires its next ``times`` checks
then disarms itself (``times=None`` fires forever until ``disarm``).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from typing import Callable, Dict, Iterator, Optional

log = logging.getLogger("npairloss_tpu.resilience")

ENV_VAR = "NPAIRLOSS_FAILPOINTS"

# Injected stall durations for the serving failpoints (seconds).  Module
# constants rather than per-arm parameters: the env-arming syntax only
# carries a count, and the alert-lifecycle tests need ONE deterministic
# magnitude comfortably above any real dispatch (0.25 s >> a warmed
# CPU top-k) yet short enough that a counted burst clears in seconds.
SERVE_LATENCY_FAULT_S = 0.25
SERVE_QUEUE_STALL_S = 0.25
# Age bump the serve.stale_model failpoint injects into the published
# model age (seconds) — far beyond any sane staleness target, so the
# watchdog fires on the first poisoned probe tick.
STALE_AGE_FAULT_S = 1e6


class InjectedFault(OSError):
    """The default fault an armed failpoint raises.

    An ``OSError`` so the transient-I/O retry paths treat an injection
    exactly like the real thing (a full disk, a flaky NFS mount)."""

    def __init__(self, name: str):
        super().__init__(f"injected fault at failpoint {name!r}")
        self.failpoint = name


class _Failpoint:
    __slots__ = ("name", "remaining", "exc_factory", "delay")

    def __init__(self, name: str, remaining: Optional[int],
                 exc_factory: Optional[Callable[[], BaseException]],
                 delay: int = 0):
        self.name = name
        self.remaining = remaining  # None = unlimited
        self.exc_factory = exc_factory
        self.delay = int(delay)  # checks to pass through before firing


_LOCK = threading.Lock()
_ARMED: Dict[str, _Failpoint] = {}
_ENV_LOADED = False


def _load_env_locked() -> None:
    global _ENV_LOADED
    if _ENV_LOADED:
        return
    _ENV_LOADED = True
    spec = os.environ.get(ENV_VAR, "")
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, count = part.partition(":")
        if not count and "@" in name:
            # "name@delay" shorthand: default count, delayed start.
            name, _, delay = name.partition("@")
        else:
            count, _, delay = count.partition("@")
        try:
            times = int(count) if count else 1
            skip = int(delay) if delay else 0
        except ValueError:
            log.warning("%s: bad count in %r — ignored", ENV_VAR, part)
            continue
        _ARMED[name] = _Failpoint(name, times, None, delay=skip)
        log.info("failpoint armed from env: %s (times=%d, delay=%d)",
                 name, times, skip)


def arm(name: str, times: Optional[int] = 1,
        exc: Optional[Callable[[], BaseException]] = None,
        delay: int = 0) -> None:
    """Arm ``name`` to fire its next ``times`` checks (None = forever).
    ``exc`` overrides the raised exception for ``fire`` sites;
    ``delay`` lets the first ``delay`` checks pass before the fires
    begin (a mid-run fault)."""
    with _LOCK:
        _load_env_locked()
        _ARMED[name] = _Failpoint(name, times, exc, delay=delay)


def disarm(name: str) -> None:
    with _LOCK:
        _ARMED.pop(name, None)


def reset() -> None:
    """Disarm everything and forget the env parse (test isolation)."""
    global _ENV_LOADED
    with _LOCK:
        _ARMED.clear()
        _ENV_LOADED = False


def _take(name: str) -> Optional[_Failpoint]:
    with _LOCK:
        _load_env_locked()
        fp = _ARMED.get(name)
        if fp is None:
            return None
        if fp.delay > 0:
            fp.delay -= 1
            return None
        if fp.remaining is not None:
            if fp.remaining <= 0:  # armed with times=0: never fires
                del _ARMED[name]
                return None
            fp.remaining -= 1
            if fp.remaining == 0:
                del _ARMED[name]
        return fp


def should_fire(name: str) -> bool:
    """True when ``name`` is armed (consumes one fire).  For call sites
    that inject by *doing* something (poisoning a value) rather than
    raising."""
    fired = _take(name) is not None
    if fired:
        log.warning("failpoint fired: %s", name)
    return fired


def fire(name: str) -> None:
    """Raise the armed fault at ``name``; no-op when unarmed."""
    fp = _take(name)
    if fp is None:
        return
    log.warning("failpoint fired: %s", name)
    raise (fp.exc_factory() if fp.exc_factory is not None
           else InjectedFault(name))


@contextlib.contextmanager
def armed(name: str, times: Optional[int] = 1,
          exc: Optional[Callable[[], BaseException]] = None) -> Iterator[None]:
    """Scoped arming — disarms on exit even when the body raises."""
    arm(name, times=times, exc=exc)
    try:
        yield
    finally:
        disarm(name)
