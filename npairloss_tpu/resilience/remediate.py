"""Alert→actuation: declarative remediation policies over the live alerts.

The observability stack measures (SLO burn, alerts); this module ACTS:
a :class:`RemediationPolicy` table binds SLO alert ids from the live
``AlertEngine`` to guarded actions — hot-swap the serving snapshot on a
staleness alert, engage load-shedding on queue saturation, request a
trainer rollback on embedding collapse, re-warm on a post-warmup
compile storm (docs/RESILIENCE.md §Remediation has the runbook).  Each
action is rate-limited by a per-policy ``cooldown_s``, bounded by
``max_attempts`` per incident, and supports a global dry-run mode that
logs what WOULD run without acting.

The lifecycle of one attempt, and the versioned audit contract
(``npairloss-remediation-v1``, ``remediation.jsonl``):

  * an alert for a policy's SLO is active and the budgets allow →
    an ``attempted`` record is appended BEFORE the action runs (a
    crash mid-action still leaves the attempt on disk);
  * the action raising fails the attempt immediately (``failed`` with
    the error);
  * otherwise the attempt stays OUTSTANDING until the triggering alert
    RESOLVES — alert resolution after the action is the one success
    signal (``succeeded``); an alert still firing a full cooldown after
    the action marks the attempt ``failed`` and (budget permitting)
    opens the next one;
  * budget exhausted with the alert still firing → the outstanding
    attempt is ``failed`` and the incident is left to the pager.

``validate_remediation_log`` IS the contract, exactly like
``validate_alert_log``: per id the lifecycle is ``attempted`` then at
most one of ``succeeded``/``failed`` (a dry-run attempt never gets an
outcome — it never acted, so it cannot have one), and with the paired
alert log every record must point at an alert that actually FIRED
before it — an action without a firing alert is refused.
``scripts/bench_check.py --remediation`` file-path-loads THIS module
from a jax-free process, so it keeps ZERO intra-package imports
(stdlib only, self-contained — the obs/live/alerts.py contract).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

log = logging.getLogger("npairloss_tpu.resilience")

REMEDIATION_SCHEMA = "npairloss-remediation-v1"
REMEDIATION_STATES = ("attempted", "succeeded", "failed")
# Twin of alerts.ALERT_SEVERITIES — spelled out, not imported (the
# jax-free file-path-load contract); pinned equal by tests.
REMEDIATION_SEVERITIES = ("info", "warning", "critical")

# Record keys every audit event carries (pinned by tests/test_remediate.py).
EVENT_KEYS = (
    "schema", "id", "policy", "action", "alert_id", "slo", "severity",
    "state", "ts", "attempt", "max_attempts", "dry_run", "message",
)


@dataclasses.dataclass(frozen=True)
class RemediationPolicy:
    """One binding: alerts of SLO ``slo`` trigger action ``action``.

    ``cooldown_s`` rate-limits the policy (minimum wall seconds between
    consecutive attempts, across incidents — an action that takes
    effect slowly must not be hammered); ``max_attempts`` bounds the
    attempts per INCIDENT (per alert_id — a new incident gets a fresh
    budget); past the budget the policy stands down and the alert is
    the pager's problem, not the actuator's.
    """

    name: str
    slo: str
    action: str
    cooldown_s: float = 30.0
    max_attempts: int = 3
    description: str = ""

    def __post_init__(self):
        for field in ("name", "slo", "action"):
            v = getattr(self, field)
            if not v or not isinstance(v, str):
                raise ValueError(
                    f"policy {self.name!r}: {field} must be a non-empty "
                    f"string, got {v!r}")
        if self.cooldown_s < 0:
            raise ValueError(
                f"policy {self.name!r}: cooldown_s must be >= 0, "
                f"got {self.cooldown_s}")
        if self.max_attempts < 1:
            raise ValueError(
                f"policy {self.name!r}: max_attempts must be >= 1, "
                f"got {self.max_attempts}")


class _Pending:
    """One outstanding (acted, not yet concluded) attempt."""

    __slots__ = ("rec_id", "policy", "alert", "attempt", "ts", "detail")

    def __init__(self, rec_id, policy, alert, attempt, ts, detail):
        self.rec_id = rec_id
        self.policy = policy
        self.alert = alert
        self.attempt = attempt
        self.ts = ts
        self.detail = detail


class RemediationEngine:
    """Consume the alert engine's active set, run guarded actions,
    append the audit log.

    ``actions`` maps action names to callables ``fn(alert_info) ->
    Optional[dict]`` (the detail lands on the success record), or
    ``(fn, undo_fn)`` pairs — ``undo_fn`` runs when the incident
    resolves (the load-shed release).  Every policy's action must be
    registered — a policy that can never act is a config error, not a
    silent no-op.  ``tick(active, now)`` is driven by the
    ``LiveObservatory`` AFTER its alert update, with the same ``now``,
    so actuation and the pager can never disagree about the alert
    state; actions run ON the tick thread (evaluation pauses while a
    hot-swap warms — bounded by the action, documented).

    ``dry_run`` logs every attempt (budgets included, so a rehearsal
    exercises the rate limits) but never calls an action.
    """

    def __init__(
        self,
        policies: Sequence[RemediationPolicy],
        actions: Mapping[str, Any],
        log_path: Optional[str] = None,
        dry_run: bool = False,
        clock=time.time,
    ):
        names = [p.name for p in policies]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate policy names: {names}")
        self.policies = list(policies)
        self._actions: Dict[str, Tuple[Callable, Optional[Callable]]] = {}
        for key, value in actions.items():
            if isinstance(value, tuple):
                fn, undo = value
            else:
                fn, undo = value, None
            self._actions[key] = (fn, undo)
        missing = sorted(
            {p.action for p in self.policies} - set(self._actions))
        if missing:
            raise ValueError(
                f"policies reference unregistered actions {missing} "
                f"(registered: {sorted(self._actions)})")
        self.dry_run = bool(dry_run)
        self._clock = clock
        # The tick runs on the evaluator thread while /healthz scrapes
        # read last_by_policy: every mutation of the state below holds
        # the lock (enforced by `staticcheck`, docs/STATICCHECK.md).
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: _lock
        self._last_attempt_ts: Dict[str, float] = {}  # guarded-by: _lock
        self._attempts: Dict[Tuple[str, str], int] = {}  # guarded-by: _lock
        self._pending: Dict[str, _Pending] = {}  # guarded-by: _lock
        self._last: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        # Outstanding UNDOs, tracked separately from pendings: an undo
        # must run when its incident resolves even if the attempt that
        # engaged it was long marked failed (a forced load-shed whose
        # budget exhausted must still be RELEASED when the alert
        # clears — an actuator that can engage but not disengage is
        # worse than no actuator).
        self._undos: Dict[str, Tuple[Callable, Dict[str, Any]]] = {}  # guarded-by: _lock
        self.history: List[Dict[str, Any]] = []  # guarded-by: _lock
        self.log_path = os.path.abspath(log_path) if log_path else None
        self._f = None
        if self.log_path:
            parent = os.path.dirname(self.log_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._resume_seq(self.log_path)
            self._f = open(self.log_path, "a", buffering=1)

    def _resume_seq(self, path: str) -> None:
        """Seed ``_seq`` past every id an appended-to log already used
        so a resumed run never collides ids.  (An attempt a previous
        segment left outstanding stays outcome-less in the log — the
        validator tolerates it and ``unresolved_remediations`` reports
        it; the new segment cannot know what became of an action it
        never ran.)"""
        try:
            records = load_remediation_log(path)
        except OSError:
            return
        for rec in records:
            if not isinstance(rec, dict):
                continue
            _, _, tail = str(rec.get("id", "")).rpartition("-")
            if tail.isdigit():
                # unguarded-ok: __init__-only, the engine is unshared
                self._seq = max(self._seq, int(tail))

    # -- the tick ----------------------------------------------------------

    def tick(self, active: Mapping[str, Mapping[str, Any]],
             now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One actuation pass over the alert engine's active set
        (``{slo: {"alert_id", "severity", "fired_at", ...}}``).
        Returns the audit events this tick appended."""
        now = self._clock() if now is None else float(now)
        events: List[Dict[str, Any]] = []
        actions_to_run: List[Tuple[RemediationPolicy, Dict[str, Any]]] = []
        undos_to_run: List[Tuple[Callable, Dict[str, Any]]] = []
        with self._lock:
            active_ids = {info.get("alert_id")
                          for info in active.values()}
            # 1) outstanding attempts whose alert resolved: the success
            # signal — conclude them; outstanding undos whose incident
            # resolved run regardless of how their attempt concluded.
            for pname, pend in list(self._pending.items()):
                if pend.alert.get("alert_id") in active_ids:
                    continue
                events.append(self._emit_outcome(
                    pend, "succeeded", now, detail=pend.detail))
                del self._pending[pname]
            for pname, (undo, alert) in list(self._undos.items()):
                if alert.get("alert_id") in active_ids:
                    continue
                del self._undos[pname]
                undos_to_run.append((undo, alert))
            # 2) policies whose SLO is burning: retry/attempt under the
            # budgets.
            for pol in self.policies:
                info = active.get(pol.slo)
                if info is None:
                    continue
                alert = {"slo": pol.slo, **dict(info)}
                aid = str(alert.get("alert_id"))
                key = (pol.name, aid)
                last = self._last_attempt_ts.get(pol.name)
                cooled = last is None or now - last >= pol.cooldown_s
                pend = self._pending.get(pol.name)
                if pend is not None:
                    if not cooled:
                        continue  # give the action time to take effect
                    # A full cooldown after the action and the alert is
                    # STILL firing: this attempt failed.
                    events.append(self._emit_outcome(
                        pend, "failed", now,
                        error=(f"alert {pend.alert.get('alert_id')} still "
                               f"firing {pol.cooldown_s:g}s after the "
                               "action")))
                    del self._pending[pol.name]
                if self._attempts.get(key, 0) >= pol.max_attempts:
                    continue  # incident budget exhausted: stand down
                if not cooled:
                    continue
                self._attempts[key] = self._attempts.get(key, 0) + 1
                self._last_attempt_ts[pol.name] = now
                self._seq += 1
                attempt = self._attempts[key]
                rec_id = f"{pol.name}-{self._seq}"
                events.append(self._emit_attempted(
                    pol, alert, rec_id, attempt, now))
                if self.dry_run:
                    continue  # logs, never acts; no outcome ever
                actions_to_run.append((pol, {
                    "rec_id": rec_id, "alert": alert, "attempt": attempt,
                    "ts": now}))
        # Actions run OUTSIDE the lock (a slow hot-swap must not block
        # the /healthz read of last_by_policy); the attempted record is
        # already on disk, so a crash inside the action is auditable.
        for pol, ctx in actions_to_run:
            fn, undo = self._actions[pol.action]
            try:
                detail = fn(ctx["alert"])
            except Exception as e:  # noqa: BLE001 — a failed action is a record
                log.error("remediation %s (%s) failed: %s",
                          pol.name, pol.action, e)
                with self._lock:
                    # Stamped at the tick's own now (never earlier than
                    # the attempted record — the audit contract), so
                    # offline replay with an injected clock stays
                    # validator-clean.
                    events.append(self._emit_outcome(
                        _Pending(ctx["rec_id"], pol, ctx["alert"],
                                 ctx["attempt"], ctx["ts"], None),
                        "failed", max(self._clock(), ctx["ts"]),
                        error=str(e)))
            else:
                with self._lock:
                    self._pending[pol.name] = _Pending(
                        ctx["rec_id"], pol, ctx["alert"], ctx["attempt"],
                        ctx["ts"], detail if isinstance(detail, dict)
                        else None)
                    if undo is not None:
                        self._undos[pol.name] = (undo, ctx["alert"])
        for undo, alert in undos_to_run:
            try:
                undo(alert)
            except Exception as e:  # noqa: BLE001 — best-effort release
                log.error("remediation undo failed: %s", e)
        return events

    # -- records -----------------------------------------------------------

    def _emit_attempted(self, pol: RemediationPolicy, alert, rec_id: str,
                        attempt: int, now: float) -> Dict[str, Any]:
        return self._emit({
            "schema": REMEDIATION_SCHEMA,
            "id": rec_id,
            "policy": pol.name,
            "action": pol.action,
            "alert_id": alert.get("alert_id"),
            "slo": pol.slo,
            "severity": alert.get("severity", "warning"),
            "state": "attempted",
            "ts": now,
            "attempt": attempt,
            "max_attempts": pol.max_attempts,
            "dry_run": self.dry_run,
            "message": (
                f"{pol.name}: {'DRY-RUN ' if self.dry_run else ''}"
                f"{pol.action} for alert {alert.get('alert_id')} "
                f"(attempt {attempt}/{pol.max_attempts})"),
        })

    def _emit_outcome(self, pend: _Pending, state: str, now: float,
                      detail: Optional[dict] = None,
                      error: Optional[str] = None) -> Dict[str, Any]:
        pol = pend.policy
        rec: Dict[str, Any] = {
            "schema": REMEDIATION_SCHEMA,
            "id": pend.rec_id,
            "policy": pol.name,
            "action": pol.action,
            "alert_id": pend.alert.get("alert_id"),
            "slo": pol.slo,
            "severity": pend.alert.get("severity", "warning"),
            "state": state,
            "ts": now,
            "attempt": pend.attempt,
            "max_attempts": pol.max_attempts,
            "dry_run": False,
            "duration_s": round(now - pend.ts, 3),
            "message": (
                f"{pol.name}: {pol.action} {state} for alert "
                f"{pend.alert.get('alert_id')}"
                + (f" — {error}" if error else "")),
        }
        if error is not None:
            rec["error"] = error
        if detail:
            rec["detail"] = detail
        return self._emit(rec)

    def _emit(self, rec: Dict[str, Any]) -> Dict[str, Any]:  # holds-lock: _lock
        self.history.append(rec)
        self._last[rec["policy"]] = rec
        if self._f is not None and not self._f.closed:
            self._f.write(json.dumps(rec) + "\n")
        log.warning("REMEDIATION %s: %s", rec["state"], rec["message"])
        return rec

    # -- reads -------------------------------------------------------------

    def last_by_policy(self) -> Dict[str, Dict[str, Any]]:
        """{policy: the last audit state} — the /healthz + drain-summary
        surface (docs/OBSERVABILITY.md §Live).  A policy that never
        fired has NO key (the freshness-JSON contract: absent means
        never, not ok).  O(policies), not O(history) — /healthz scrapes
        this under the engine lock the tick path shares."""
        with self._lock:
            return {
                policy: {
                    "action": rec["action"],
                    "outcome": rec["state"],
                    "alert_id": rec["alert_id"],
                    "wall_time": rec["ts"],
                    **({"dry_run": True} if rec.get("dry_run") else {}),
                }
                for policy, rec in self._last.items()
            }

    def close(self) -> None:
        with self._lock:
            if self._f is not None and not self._f.closed:
                self._f.close()


# -- policy tables ------------------------------------------------------------

_POLICY_KEYS = {f.name for f in dataclasses.fields(RemediationPolicy)}


def default_policies(kind: str) -> List[RemediationPolicy]:
    """The shipped policy tables, bound to the default watchdog SLO
    names (obs/live/watchdogs.py) and the action names the CLI
    registers (docs/RESILIENCE.md §Remediation has the inventory)."""
    if kind == "serve":
        return [
            RemediationPolicy(
                name="hotswap_model", slo="model_staleness",
                action="snapshot_hotswap", cooldown_s=30.0,
                max_attempts=3,
                description="hot-swap to the newest committed snapshot "
                            "when the served model goes stale"),
            RemediationPolicy(
                name="hotswap_index", slo="index_staleness",
                action="snapshot_hotswap", cooldown_s=30.0,
                max_attempts=3,
                description="republish the newest committed gallery "
                            "index when the served one goes stale"),
            RemediationPolicy(
                name="load_shed", slo="serve_queue_saturation",
                action="load_shed", cooldown_s=10.0, max_attempts=5,
                description="engage admission shedding while the queue "
                            "saturates; released when the alert clears"),
            RemediationPolicy(
                name="rewarm", slo="serve_post_warmup_compile",
                action="rewarm", cooldown_s=120.0, max_attempts=2,
                description="re-warm every padding bucket after a "
                            "post-warmup compile storm"),
            RemediationPolicy(
                name="probe_escalation", slo="serve_recall_floor",
                action="escalate_probes", cooldown_s=30.0,
                max_attempts=4,
                description="widen the IVF probe set while the shadow "
                            "recall estimate burns; past the probe "
                            "budget, fall back to flat exact scoring"),
        ]
    if kind == "train":
        return [
            RemediationPolicy(
                name="trainer_rollback", slo="embedding_collapse",
                action="trainer_rollback", cooldown_s=120.0,
                max_attempts=2,
                description="roll the trainer back to a pre-incident "
                            "snapshot on embedding collapse"),
        ]
    raise ValueError(
        f"unknown policy kind {kind!r} (expected 'serve' or 'train')")


def load_policies(path: str) -> List[RemediationPolicy]:
    """Parse a remediation config file::

        {"policies": [
          {"name": "hotswap_model", "slo": "model_staleness",
           "action": "snapshot_hotswap", "cooldown_s": 30,
           "max_attempts": 3}
        ]}

    Validation is loud — a typo'd key or an empty table must fail at
    load, not silently never remediate."""
    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: remediation config must be an object")
    unknown = set(raw) - {"policies"}
    if unknown:
        raise ValueError(
            f"{path}: unknown top-level keys {sorted(unknown)}")
    entries = raw.get("policies")
    if not isinstance(entries, list) or not entries:
        raise ValueError(f"{path}: config defines no policies")
    out: List[RemediationPolicy] = []
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise ValueError(f"{path}: policies[{i}] is not an object")
        bad = set(entry) - _POLICY_KEYS
        if bad:
            raise ValueError(
                f"{path}: policies[{i}] unknown keys {sorted(bad)} "
                f"(known: {sorted(_POLICY_KEYS)})")
        missing = {"name", "slo", "action"} - set(entry)
        if missing:
            raise ValueError(
                f"{path}: policies[{i}] missing {sorted(missing)}")
        out.append(RemediationPolicy(**entry))
    names = [p.name for p in out]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate policy names: {names}")
    return out


# -- the npairloss-remediation-v1 contract ------------------------------------


def load_remediation_log(path: str) -> List[Dict[str, Any]]:
    """Read one audit JSONL file; a torn final line (killed writer) is
    tolerated, any other unparseable line surfaces through the
    validator via a sentinel record (the alert-log loader's contract)."""
    records: List[Dict[str, Any]] = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            if i == len(lines) - 1:
                continue  # torn tail: the crash-durability contract
            records.append({"_bad_line": i + 1})
    return records


def validate_remediation_log(
    records: Sequence[Any],
    alert_records: Optional[Sequence[Dict[str, Any]]] = None,
) -> Optional[str]:
    """Schema + lifecycle check; returns an error string or None.

    The contract: every record carries :data:`EVENT_KEYS` with the
    schema tag, a known state/severity, numeric ts, integer
    ``1 <= attempt <= max_attempts``; per id the lifecycle is
    ``attempted`` then at most ONE outcome (``succeeded``/``failed``),
    with ``outcome.ts >= attempted.ts``, a ``duration_s`` on every
    outcome and an ``error`` on every failure; a dry-run attempt never
    has an outcome (it never acted).  With ``alert_records`` (a
    validated ``npairloss-alerts-v1`` stream) every record must point
    at an alert that FIRED at or before the record's ts — an action
    without a firing alert is refused.
    """
    fired_at: Dict[str, float] = {}
    if alert_records is not None:
        for rec in alert_records:
            if isinstance(rec, dict) and rec.get("state") == "firing":
                fired_at[str(rec.get("alert_id"))] = float(
                    rec.get("ts", 0.0))
    lifecycles: Dict[str, List[Dict[str, Any]]] = {}
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            return f"record {i} is not an object"
        if "_bad_line" in rec:
            return f"unparseable JSON on line {rec['_bad_line']}"
        if rec.get("schema") != REMEDIATION_SCHEMA:
            return (f"record {i}: schema must be {REMEDIATION_SCHEMA!r}, "
                    f"got {rec.get('schema')!r}")
        for key in EVENT_KEYS:
            if key not in rec:
                return f"record {i} missing {key!r}"
        if rec["state"] not in REMEDIATION_STATES:
            return (f"record {i}: state {rec['state']!r} not in "
                    f"{REMEDIATION_STATES}")
        if rec["severity"] not in REMEDIATION_SEVERITIES:
            return (f"record {i}: severity {rec['severity']!r} not in "
                    f"{REMEDIATION_SEVERITIES}")
        if not isinstance(rec["ts"], (int, float)):
            return f"record {i}: ts is not numeric"
        if not isinstance(rec["dry_run"], bool):
            return f"record {i}: dry_run is not a bool"
        for key in ("attempt", "max_attempts"):
            if not isinstance(rec[key], int) or isinstance(rec[key], bool):
                return f"record {i}: {key} is not an integer"
        if not (1 <= rec["attempt"] <= rec["max_attempts"]):
            return (f"record {i}: attempt {rec['attempt']} outside "
                    f"[1, max_attempts {rec['max_attempts']}]")
        rid, state = rec["id"], rec["state"]
        seen = lifecycles.setdefault(rid, [])
        if state == "attempted":
            if seen:
                return f"record {i}: duplicate attempted for id {rid!r}"
        else:
            if not seen:
                return (f"record {i}: {state} for id {rid!r} without an "
                        "attempted record")
            if any(r["state"] != "attempted" for r in seen):
                return (f"record {i}: second outcome for id {rid!r} "
                        "(lifecycle is attempted then at most one of "
                        "succeeded|failed)")
            att = seen[0]
            if att["dry_run"]:
                return (f"record {i}: outcome for DRY-RUN id {rid!r} — "
                        "a dry run never acts, so it cannot succeed or "
                        "fail")
            if rec["ts"] < att["ts"]:
                return (f"record {i}: outcome ts {rec['ts']} precedes "
                        f"its attempted ts {att['ts']}")
            if not isinstance(rec.get("duration_s"), (int, float)):
                return f"record {i}: outcome missing numeric duration_s"
            if state == "failed" and not isinstance(rec.get("error"), str):
                return f"record {i}: failed record missing error"
        if alert_records is not None:
            aid = str(rec.get("alert_id"))
            if aid not in fired_at:
                return (f"record {i}: action for alert {aid!r} which "
                        "never fired in the alert log (action-without-"
                        "alert refused)")
            if float(rec["ts"]) < fired_at[aid]:
                return (f"record {i}: action ts {rec['ts']} precedes the "
                        f"firing of alert {aid!r} at {fired_at[aid]}")
        seen.append(rec)
    return None


def unresolved_remediations(records: Sequence[Dict[str, Any]]
                            ) -> List[Tuple[str, str, str]]:
    """(id, policy, alert_id) of non-dry attempts with no outcome at end
    of log — a process killed mid-action, or drained before the success
    signal arrived.  Reported, not gated (the alert gate already owns
    the unresolved-incident verdict).  Call only on a validated log."""
    pending: Dict[str, Tuple[str, str, str]] = {}
    for rec in records:
        if rec["state"] == "attempted":
            if not rec["dry_run"]:
                pending[rec["id"]] = (
                    rec["id"], rec["policy"], str(rec["alert_id"]))
        else:
            pending.pop(rec["id"], None)
    return list(pending.values())


def abandoned_remediations(
    records: Sequence[Dict[str, Any]],
    resolved_alert_ids: Optional[Sequence[str]] = None,
) -> List[Tuple[str, str, str]]:
    """(id, policy, alert_id) of CRITICAL incidents whose LAST attempt
    failed with budget remaining and no later attempt — the engine (or
    its operator) gave up early.  This is what the bench_check gate
    refuses: a failed critical remediation with attempts remaining is
    an actuator walking away from a LIVE incident, not an exhausted
    budget.  ``resolved_alert_ids`` (from the paired alert log) excuses
    incidents that RESOLVED anyway — an alert that healed after a
    failed attempt needed no retry, and the audit log alone cannot
    record that (resolution after a concluded-failed attempt emits no
    event).  Call only on a validated log."""
    resolved = {str(a) for a in (resolved_alert_ids or ())}
    last: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for rec in records:
        last[(rec["policy"], str(rec["alert_id"]))] = rec
    out: List[Tuple[str, str, str]] = []
    for (policy, aid), rec in last.items():
        if (rec["state"] == "failed"
                and rec["severity"] == "critical"
                and rec["attempt"] < rec["max_attempts"]
                and aid not in resolved):
            out.append((rec["id"], policy, aid))
    return out
