"""Fault-tolerance subsystem (docs/RESILIENCE.md).

Multi-day metric-learning runs on a pod die to preemptions, transient
I/O, data-worker crashes, and numeric divergence long before they die
to bugs.  This package makes the Solver survive all four:

  * ``resilience.snapshot`` — atomic snapshot commit (tmp dir + per-
    array checksum manifest + fsync + rename), torn-snapshot
    validation, newest-valid discovery, retention GC;
  * ``resilience.retrying`` — jittered exponential backoff around
    snapshot I/O and worker respawn;
  * ``resilience.preempt`` — SIGTERM/SIGINT -> finish the step,
    emergency snapshot, exit :data:`EXIT_PREEMPTED` so a supervisor
    relaunches with ``--resume auto``;
  * ``resilience.guard`` — N consecutive non-finite losses -> rollback
    to the last valid snapshot (optionally lr-scaled) or halt;
  * ``resilience.failpoints`` — named fault-injection points
    (``NPAIRLOSS_FAILPOINTS`` env or programmatic) that make every
    behavior above deterministically testable without real faults.

``failpoints``/``retrying`` are jax-free; ``snapshot`` needs jax for
tree flattening only.  Recovery events (``retry``/``rollback``/
``preempt``/``resume_skip``) flow through ``obs.run.RunTelemetry``.
"""

from npairloss_tpu.resilience import failpoints
from npairloss_tpu.resilience.failpoints import InjectedFault
from npairloss_tpu.resilience.guard import (
    DivergenceConfig,
    DivergenceError,
    DivergenceGuard,
    RollbackRequest,
)
from npairloss_tpu.resilience.remediate import (
    RemediationEngine,
    RemediationPolicy,
    load_remediation_log,
    validate_remediation_log,
)
from npairloss_tpu.resilience.preempt import (
    EXIT_PREEMPTED,
    PreemptionSignal,
    TrainingPreempted,
)
from npairloss_tpu.resilience.retrying import RetryPolicy, call_with_retry
from npairloss_tpu.resilience.snapshot import (
    SnapshotError,
    SnapshotValidationError,
    commit_snapshot,
    gc_snapshots,
    list_snapshots,
    quarantine_snapshots,
    read_manifest,
    state_checksums,
    validate_snapshot,
    validate_snapshot_wait,
    verify_restored,
)

__all__ = [
    "EXIT_PREEMPTED",
    "DivergenceConfig",
    "DivergenceError",
    "DivergenceGuard",
    "InjectedFault",
    "PreemptionSignal",
    "RemediationEngine",
    "RemediationPolicy",
    "RetryPolicy",
    "RollbackRequest",
    "SnapshotError",
    "SnapshotValidationError",
    "TrainingPreempted",
    "call_with_retry",
    "commit_snapshot",
    "failpoints",
    "gc_snapshots",
    "list_snapshots",
    "load_remediation_log",
    "quarantine_snapshots",
    "read_manifest",
    "state_checksums",
    "validate_remediation_log",
    "validate_snapshot",
    "validate_snapshot_wait",
    "verify_restored",
]
