"""Segment-based write-ahead log — artifact ``npairloss-wal-v1``.

The serving tier acknowledges an ingest only after the record is
*durable* here: length-prefixed, CRC-32-checksummed records appended to
an active segment file, group-commit fsynced (a background flusher
amortizes the fsync across a configurable interval; ``wait_durable``
blocks the ack until the fsync covering its sequence number lands).
Segment create/rotate fsyncs the parent directory entry, so a crash
immediately after rotation cannot lose the new segment's name.

Artifact layout (``npairloss-wal-v1``)::

    wal_dir/
      wal_manifest.json        # {"format", "segment_max_bytes", "sealed"}
      wal-0000000000000001.seg # records for seq 1..N (name = first seq)
      wal-0000000000000NNN.seg # active segment (unsealed)

Record framing: ``<II`` little-endian header = (payload length, CRC-32
of payload), then the JSON payload bytes.  Every payload is an object
carrying its ``seq`` (assigned monotonically by ``append``); ingest
records use ``kind: "add"`` with ``ids``/``labels``/``dim``/``emb``
(base64 float32 — the encoding is the caller's, this module stays
numpy-free).  On rotation the finished segment is *sealed* into the
manifest (first/last seq + whole-file CRC, manifest rewritten
atomically): a sealed segment that later fails its CRC or loses its
tail is tampering, not a crash, and is refused.

Recovery semantics (``WriteAheadLog`` open):

  * a torn tail — a partial header, short payload, or CRC mismatch at
    the very end of the FINAL (unsealed) segment — is a crash artifact:
    it is truncated LOUDLY (logged, counted in ``torn_records`` /
    ``torn_bytes``), never silently absorbed;
  * the same damage anywhere else (mid-stream, or in a sealed segment)
    is corruption and raises :class:`WalCorruptionError`;
  * sequence numbers must be contiguous within and across segments; a
    missing *prefix* of segments is a GC artifact and fine, a missing
    middle segment is a gap and refused.

Exactly-once replay is the watermark contract: index snapshots publish
the last sequence number they contain (``ingest_watermark`` in the
index manifest), recovery replays only records ABOVE the snapshot's
watermark, and :meth:`WriteAheadLog.gc` deletes sealed segments once a
published watermark covers their last record.

Like every ``npairloss-*-v1`` contract, this module is **stdlib-only
and self-contained**: jax-free gate processes (scripts/bench_check.py
``--wal``) load it by file path without importing the package — pinned
by the staticcheck purity pass (npairloss_tpu/analysis/purity.py).
The failpoint/retry imports below resolve to stdlib-pure siblings
(pre-seeded by the gate loader) and degrade to None when absent.
"""

from __future__ import annotations

import json
import logging
import os
import re
import struct
import threading
import time
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

try:  # stdlib-pure siblings; absent under bare file-path loads
    from npairloss_tpu.resilience import failpoints
except ImportError:  # pragma: no cover - gate loads without package
    failpoints = None  # type: ignore[assignment]

try:
    from npairloss_tpu.resilience.retrying import (
        call_with_retry,
        named_policy,
    )
except ImportError:  # pragma: no cover - gate loads without package
    call_with_retry = None  # type: ignore[assignment]
    named_policy = None  # type: ignore[assignment]

log = logging.getLogger("npairloss_tpu.resilience")

WAL_FORMAT = "npairloss-wal-v1"
MANIFEST_NAME = "wal_manifest.json"
MANIFEST_KEYS = ("format", "segment_max_bytes", "sealed")
SEAL_KEYS = ("first_seq", "last_seq", "crc32")

_HEADER = struct.Struct("<II")  # (payload length, CRC-32 of payload)
_SEG_RE = re.compile(r"^wal-(\d{16})\.seg$")


class WalError(RuntimeError):
    """Operational WAL failure (timeouts, closed log, bad arguments)."""


class WalCorruptionError(WalError):
    """The on-disk artifact violates the ``npairloss-wal-v1`` contract
    in a way a crash cannot explain — refused, never repaired."""


def _segment_name(first_seq: int) -> str:
    return f"wal-{first_seq:016d}.seg"


def _fsync_dir(path: str) -> None:
    """fsync a directory entry table; best-effort on filesystems that
    refuse directory handles (the same posture as snapshot.py)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _encode_record(payload: Dict[str, Any]) -> bytes:
    data = json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return _HEADER.pack(len(data), zlib.crc32(data) & 0xFFFFFFFF) + data


def _list_segments(path: str) -> List[Tuple[int, str]]:
    """Sorted ``(first_seq, filename)`` for every well-formed segment
    name; a ``wal-*.seg`` name that does not parse is corruption."""
    out: List[Tuple[int, str]] = []
    for name in os.listdir(path):
        m = _SEG_RE.match(name)
        if m:
            out.append((int(m.group(1)), name))
        elif name.startswith("wal-") and name.endswith(".seg"):
            raise WalCorruptionError(f"malformed segment name: {name}")
    out.sort()
    return out


def _read_segment(path: str) -> Tuple[List[Tuple[int, Dict[str, Any]]],
                                      int, Optional[str], int]:
    """Scan one segment file: ``(records, good_end_offset, damage,
    file_crc32)``.  ``records`` is ``[(seq, payload), ...]`` up to the
    last intact record; ``damage`` describes the first torn/corrupt
    byte range (None when the file is clean).  The caller decides
    whether damage is a truncatable tail or refusable corruption —
    this scanner only reports."""
    records: List[Tuple[int, Dict[str, Any]]] = []
    good_end = 0
    crc = 0
    with open(path, "rb") as f:
        blob = f.read()
    size = len(blob)
    off = 0
    while off < size:
        if off + _HEADER.size > size:
            return records, good_end, (
                f"partial header at offset {off} "
                f"({size - off} byte(s))"), crc
        length, want = _HEADER.unpack_from(blob, off)
        body_at = off + _HEADER.size
        if body_at + length > size:
            return records, good_end, (
                f"partial payload at offset {off} "
                f"({size - off} of {_HEADER.size + length} byte(s))"), crc
        body = blob[body_at:body_at + length]
        if zlib.crc32(body) & 0xFFFFFFFF != want:
            return records, good_end, (
                f"CRC mismatch at offset {off}"), crc
        try:
            payload = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return records, good_end, (
                f"unparseable payload at offset {off}"), crc
        if not isinstance(payload, dict) or \
                not isinstance(payload.get("seq"), int):
            return records, good_end, (
                f"payload without an integer seq at offset {off}"), crc
        rec = blob[off:body_at + length]
        crc = zlib.crc32(rec, crc) & 0xFFFFFFFF
        records.append((payload["seq"], payload))
        off = body_at + length
        good_end = off
    return records, good_end, None, crc


def validate_record_payload(payload: Any) -> Optional[str]:
    """None when ``payload`` is a well-formed record body; else the
    violation.  ``kind: "add"`` records additionally pin the ingest
    schema (ids/labels the same length, a positive dim, base64 emb)."""
    if not isinstance(payload, dict):
        return f"record payload must be an object, got {type(payload).__name__}"
    if not isinstance(payload.get("seq"), int) or payload["seq"] < 1:
        return f"record seq must be a positive int, got {payload.get('seq')!r}"
    if payload.get("kind") == "add":
        ids, labels = payload.get("ids"), payload.get("labels")
        if not isinstance(ids, list) or not isinstance(labels, list) \
                or len(ids) != len(labels) or not ids:
            return (f"add record seq {payload['seq']}: ids/labels must be "
                    "non-empty lists of equal length")
        dim = payload.get("dim")
        if not isinstance(dim, int) or dim < 1:
            return (f"add record seq {payload['seq']}: dim must be a "
                    f"positive int, got {dim!r}")
        if not isinstance(payload.get("emb"), str):
            return (f"add record seq {payload['seq']}: emb must be a "
                    "base64 string")
    return None


def validate_wal_manifest(obj: Any) -> Optional[str]:
    """None when ``obj`` is a well-formed ``npairloss-wal-v1`` manifest;
    else the first violation."""
    if not isinstance(obj, dict):
        return f"manifest must be an object, got {type(obj).__name__}"
    if obj.get("format") != WAL_FORMAT:
        return (f"manifest format must be {WAL_FORMAT!r}, "
                f"got {obj.get('format')!r}")
    for key in MANIFEST_KEYS:
        if key not in obj:
            return f"manifest missing key: {key}"
    if not isinstance(obj["segment_max_bytes"], int) or \
            obj["segment_max_bytes"] < _HEADER.size + 2:
        return ("manifest segment_max_bytes must be an int larger than "
                f"one record header, got {obj['segment_max_bytes']!r}")
    sealed = obj["sealed"]
    if not isinstance(sealed, dict):
        return "manifest sealed must be an object"
    for name, seal in sealed.items():
        m = _SEG_RE.match(name)
        if not m:
            return f"sealed entry for malformed segment name: {name}"
        if not isinstance(seal, dict):
            return f"sealed[{name}] must be an object"
        for key in SEAL_KEYS:
            if not isinstance(seal.get(key), int):
                return f"sealed[{name}] missing int key: {key}"
        if seal["first_seq"] != int(m.group(1)):
            return (f"sealed[{name}] first_seq {seal['first_seq']} "
                    "disagrees with the segment name")
        if seal["last_seq"] < seal["first_seq"]:
            return (f"sealed[{name}] last_seq {seal['last_seq']} < "
                    f"first_seq {seal['first_seq']}")
    return None


def load_wal_manifest(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, MANIFEST_NAME), "r",
              encoding="utf-8") as f:
        return json.load(f)


def wal_info(path: str) -> Dict[str, Any]:
    """Scan a WAL directory without mutating it: record/segment counts,
    the last replayable seq, and any torn tail on the final segment.
    Raises :class:`WalCorruptionError` on contract violations (a torn
    tail on the FINAL segment is a crash artifact and reported, not
    raised)."""
    manifest = load_wal_manifest(path)
    err = validate_wal_manifest(manifest)
    if err is not None:
        raise WalCorruptionError(err)
    sealed = manifest["sealed"]
    segments = _list_segments(path)
    present = {name for _, name in segments}
    records = 0
    first_seq: Optional[int] = None
    last_seq = 0
    torn_bytes = 0
    torn_segment: Optional[str] = None
    torn_detail: Optional[str] = None
    expect: Optional[int] = None
    for i, (name_seq, name) in enumerate(segments):
        seal = sealed.get(name)
        is_last = i == len(segments) - 1
        if expect is not None and name_seq != expect:
            raise WalCorruptionError(
                f"segment {name} starts at seq {name_seq}, expected "
                f"{expect} — sequence gap across segments")
        recs, good_end, damage, crc = _read_segment(
            os.path.join(path, name))
        if damage is not None:
            if not is_last or seal is not None:
                raise WalCorruptionError(
                    f"segment {name}: {damage} — damage outside the "
                    "final unsealed segment is corruption, not a torn "
                    "tail")
            torn_segment, torn_detail = name, damage
            torn_bytes = os.path.getsize(os.path.join(path, name)) \
                - good_end
        seq = name_seq
        for rec_seq, payload in recs:
            if rec_seq != seq:
                raise WalCorruptionError(
                    f"segment {name}: record seq {rec_seq}, expected "
                    f"{seq} — sequence gap or regression")
            perr = validate_record_payload(payload)
            if perr is not None:
                raise WalCorruptionError(f"segment {name}: {perr}")
            seq += 1
        if recs:
            if first_seq is None:
                first_seq = recs[0][0]
            last_seq = recs[-1][0]
            records += len(recs)
        if seal is not None:
            if damage is not None or seal["last_seq"] != (
                    recs[-1][0] if recs else seal["first_seq"] - 1):
                raise WalCorruptionError(
                    f"sealed segment {name} does not end at its sealed "
                    f"last_seq {seal['last_seq']} — truncated or "
                    "extended after sealing")
            if seal["crc32"] != crc:
                raise WalCorruptionError(
                    f"sealed segment {name}: file CRC {crc} != sealed "
                    f"CRC {seal['crc32']} — content changed after "
                    "sealing")
        expect = seq
    stale = [name for name in sealed if name not in present]
    for name in stale:
        # GC unlinks segments before the manifest rewrite lands; a
        # sealed entry whose file is gone is only explainable as that
        # crash when every surviving record sits ABOVE the sealed range.
        seal = sealed[name]
        if first_seq is not None and seal["last_seq"] >= first_seq:
            raise WalCorruptionError(
                f"sealed segment {name} is missing but overlaps the "
                f"surviving records (sealed last_seq {seal['last_seq']} "
                f">= first surviving seq {first_seq}) — a hole, not GC")
    return {
        "format": WAL_FORMAT,
        "segments": len(segments),
        "records": records,
        "first_seq": first_seq if first_seq is not None else 0,
        "last_seq": last_seq,
        "torn_tail": torn_segment is not None,
        "torn_segment": torn_segment,
        "torn_detail": torn_detail,
        "torn_bytes": torn_bytes,
        "stale_seals": len(stale),
    }


def validate_wal_dir(path: str,
                     min_last_seq: Optional[int] = None) -> Optional[str]:
    """None when ``path`` holds a valid ``npairloss-wal-v1`` artifact;
    else the first violation.  A torn tail on the final segment is a
    crash artifact and passes; ``min_last_seq`` additionally refuses a
    log whose last replayable record falls short of an externally
    acknowledged sequence number (a truncated-then-patched copy)."""
    if not os.path.isdir(path):
        return f"not a directory: {path}"
    if not os.path.exists(os.path.join(path, MANIFEST_NAME)):
        return f"missing {MANIFEST_NAME} in {path}"
    try:
        info = wal_info(path)
    except WalCorruptionError as e:
        return str(e)
    except (OSError, ValueError) as e:
        return f"unreadable WAL artifact: {e}"
    if min_last_seq is not None and info["last_seq"] < min_last_seq:
        return (f"last replayable seq {info['last_seq']} < acknowledged "
                f"watermark {min_last_seq} — acknowledged records are "
                "missing from the log")
    return None


class WriteAheadLog:
    """Append-only segmented WAL with group-commit fsync.

    ``flush_interval_s > 0`` starts a background flusher that fsyncs
    the active segment every interval; ``append`` returns immediately
    and :meth:`wait_durable` blocks the ack until the covering fsync
    lands.  ``flush_interval_s <= 0`` fsyncs inline on every append
    (the strict mode the crash-matrix tests pin)."""

    def __init__(self, path: str, *, flush_interval_s: float = 0.0,
                 segment_max_bytes: int = 1 << 20):
        self.path = os.path.abspath(path)
        self.flush_interval_s = float(flush_interval_s)
        self.torn_records = 0
        self.torn_bytes = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._file: Optional[Any] = None
        self._closed = False
        self._seq = 0           # last assigned
        self._written_seq = 0   # last fully written to the OS
        self._durable_seq = 0   # last covered by an fsync
        self._active_first = 1
        self._active_size = 0
        self._active_crc = 0
        if not os.path.isdir(self.path):
            os.makedirs(self.path, exist_ok=True)
            _fsync_dir(os.path.dirname(self.path) or ".")
        manifest_path = os.path.join(self.path, MANIFEST_NAME)
        if os.path.exists(manifest_path):
            manifest = load_wal_manifest(self.path)
            err = validate_wal_manifest(manifest)
            if err is not None:
                raise WalCorruptionError(err)
            self.segment_max_bytes = int(manifest["segment_max_bytes"])
            self._sealed: Dict[str, Dict[str, int]] = dict(
                manifest["sealed"])
        else:
            self.segment_max_bytes = int(segment_max_bytes)
            self._sealed = {}
            self._write_manifest_locked()
        self._recover()
        self._flusher: Optional[threading.Thread] = None
        if self.flush_interval_s > 0:
            self._flusher = threading.Thread(
                target=self._flush_loop, name="wal-flusher", daemon=True)
            self._flusher.start()

    # -- open/recovery -------------------------------------------------------

    def _recover(self) -> None:
        segments = _list_segments(self.path)
        present = {name for _, name in segments}
        stale = [n for n in self._sealed if n not in present]
        expect: Optional[int] = None
        last_good_end = 0
        for i, (name_seq, name) in enumerate(segments):
            full = os.path.join(self.path, name)
            seal = self._sealed.get(name)
            is_last = i == len(segments) - 1
            if expect is not None and name_seq != expect:
                raise WalCorruptionError(
                    f"segment {name} starts at seq {name_seq}, expected "
                    f"{expect} — sequence gap across segments")
            recs, good_end, damage, crc = _read_segment(full)
            if damage is not None and (not is_last or seal is not None):
                raise WalCorruptionError(
                    f"segment {name}: {damage} — damage outside the "
                    "final unsealed segment is corruption, not a torn "
                    "tail")
            seq = name_seq
            for rec_seq, _ in recs:
                if rec_seq != seq:
                    raise WalCorruptionError(
                        f"segment {name}: record seq {rec_seq}, "
                        f"expected {seq} — sequence gap or regression")
                seq += 1
            if seal is not None:
                ends_at = recs[-1][0] if recs else seal["first_seq"] - 1
                if seal["last_seq"] != ends_at or seal["crc32"] != crc:
                    raise WalCorruptionError(
                        f"sealed segment {name} disagrees with its seal "
                        f"(last_seq {ends_at} vs {seal['last_seq']}, "
                        f"CRC {crc} vs {seal['crc32']}) — content "
                        "changed after sealing")
            if recs:
                if self._seq and recs[0][0] > self._seq + 1:
                    raise WalCorruptionError(
                        f"segment {name} jumps from seq {self._seq} to "
                        f"{recs[0][0]}")
                self._seq = recs[-1][0]
            if damage is not None:
                size = os.path.getsize(full)
                lost = size - good_end
                self.torn_records += 1
                self.torn_bytes += lost
                log.warning(
                    "wal: torn tail in %s truncated at offset %d "
                    "(%d byte(s) dropped: %s)", name, good_end, lost,
                    damage)
                with open(full, "r+b") as f:
                    f.truncate(good_end)
                    f.flush()
                    os.fsync(f.fileno())
            if is_last:
                self._active_first = name_seq
                self._active_size = good_end
                self._active_crc = crc
                last_good_end = good_end
            expect = seq
        for name in stale:
            seal = self._sealed[name]
            floor = segments[0][0] if segments else self._seq + 1
            if seal["last_seq"] >= floor:
                raise WalCorruptionError(
                    f"sealed segment {name} is missing but overlaps the "
                    "surviving records — a hole, not GC")
            log.warning("wal: dropping stale seal for GC'd segment %s",
                        name)
            del self._sealed[name]
        if stale:
            self._write_manifest_locked()
        if segments and segments[-1][1] not in self._sealed:
            last = os.path.join(self.path, segments[-1][1])
            self._file = open(last, "ab")
            if self._file.tell() != last_good_end:  # pragma: no cover
                raise WalError(
                    f"append position {self._file.tell()} != recovered "
                    f"end {last_good_end} for {last}")
        else:
            # Fresh log, or a rotation that crashed after sealing the
            # old segment but before creating its successor: appending
            # to a sealed segment would break its seal, so start a new
            # one at the next sequence number.
            self._create_segment_locked(self._seq + 1)
        self._written_seq = self._seq
        self._durable_seq = self._seq

    # -- manifest / segments -------------------------------------------------

    def _write_manifest_locked(self) -> None:
        manifest = {"format": WAL_FORMAT,
                    "segment_max_bytes": self.segment_max_bytes,
                    "sealed": dict(sorted(self._sealed.items()))}
        final = os.path.join(self.path, MANIFEST_NAME)
        tmp = final + ".part"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, sort_keys=True, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        _fsync_dir(self.path)

    def _create_segment_locked(self, first_seq: int) -> None:
        path = os.path.join(self.path, _segment_name(first_seq))
        self._file = open(path, "xb")
        _fsync_dir(self.path)
        self._active_first = first_seq
        self._active_size = 0
        self._active_crc = 0

    def _rotate_locked(self, next_first_seq: int) -> None:
        assert self._file is not None
        self._file.flush()
        os.fsync(self._file.fileno())
        self._durable_seq = self._written_seq
        self._cond.notify_all()
        name = _segment_name(self._active_first)
        self._file.close()
        self._file = None
        if failpoints is not None:
            # Crash point: the finished segment is fsynced but its seal
            # has not reached the manifest — recovery treats it as the
            # (clean) unsealed tail and re-rotates on the next append.
            failpoints.fire("wal.rotate.crash")
        self._sealed[name] = {"first_seq": self._active_first,
                              "last_seq": self._written_seq,
                              "crc32": self._active_crc}
        self._write_manifest_locked()
        self._create_segment_locked(next_first_seq)

    # -- append / durability -------------------------------------------------

    def append(self, payload: Dict[str, Any]) -> int:
        """Assign the next sequence number, frame and write the record.
        Durability is NOT implied unless the log runs in inline-fsync
        mode — acknowledge only after :meth:`wait_durable`."""
        with self._lock:
            if self._closed or self._file is None:
                raise WalError("append on a closed WAL")
            seq = self._seq + 1
            body = dict(payload)
            body["seq"] = seq
            err = validate_record_payload(body)
            if err is not None:
                raise WalError(err)
            rec = _encode_record(body)
            if self._active_size > 0 and \
                    self._active_size + len(rec) > self.segment_max_bytes:
                self._rotate_locked(seq)
            if failpoints is not None and \
                    failpoints.should_fire("wal.append.torn"):
                # Crash point: die mid-record-write — the classic torn
                # tail recovery must truncate loudly.
                self._file.write(rec[:max(1, len(rec) // 2)])
                self._file.flush()
                os.fsync(self._file.fileno())
                raise failpoints.InjectedFault("wal.append.torn")
            self._file.write(rec)
            self._seq = seq
            self._written_seq = seq
            self._active_size += len(rec)
            self._active_crc = zlib.crc32(rec, self._active_crc) \
                & 0xFFFFFFFF
            if self.flush_interval_s <= 0:
                self._fsync_locked()
            return seq

    def _fsync_locked(self) -> None:
        if self._file is None:
            return
        self._file.flush()
        os.fsync(self._file.fileno())
        self._durable_seq = self._written_seq
        self._cond.notify_all()

    def flush(self) -> int:
        """Group-commit fsync: everything appended so far becomes
        durable.  Returns the new durable sequence number."""
        with self._lock:
            self._fsync_locked()
            return self._durable_seq

    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                self._cond.wait(timeout=self.flush_interval_s)
                if self._closed:
                    return
                if self._durable_seq < self._written_seq:
                    self._fsync_locked()

    def wait_durable(self, seq: int, timeout: float = 30.0) -> None:
        """Block until the fsync covering ``seq`` lands (the ack
        barrier).  Raises :class:`WalError` on timeout or close."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._durable_seq < seq:
                if self._closed:
                    raise WalError("WAL closed before seq became durable")
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise WalError(
                        f"timed out waiting for seq {seq} to become "
                        f"durable (durable_seq={self._durable_seq})")
                self._cond.wait(timeout=remaining
                                if remaining is not None else 0.1)

    # -- replay / GC ---------------------------------------------------------

    def replay(self, after_seq: int = 0) -> Iterator[Dict[str, Any]]:
        """Yield record payloads with ``seq > after_seq`` in order — the
        exactly-once half of the watermark contract (the caller supplies
        the snapshot's committed watermark).  Segment opens run under
        the named ``wal_replay`` retry policy."""
        with self._lock:
            segments = _list_segments(self.path)
            sealed = dict(self._sealed)
            self._fsync_locked()
        for _, name in segments:
            seal = sealed.get(name)
            if seal is not None and seal["last_seq"] <= after_seq:
                continue
            full = os.path.join(self.path, name)
            if call_with_retry is not None and named_policy is not None:
                recs, _, damage, _ = call_with_retry(
                    lambda p=full: _read_segment(p),
                    named_policy("wal_replay"),
                    describe=f"wal replay of {name}")
            else:  # pragma: no cover - bare file-path-load fallback
                recs, _, damage, _ = _read_segment(full)
            if damage is not None:
                raise WalCorruptionError(
                    f"segment {name}: {damage} during replay — recovery "
                    "must run (and truncate) before replay")
            for seq, payload in recs:
                if seq > after_seq:
                    yield payload

    def gc(self, watermark: int) -> int:
        """Unlink sealed segments whose LAST record a published
        snapshot watermark covers; the active segment is never GC'd.
        Returns the number of segments removed."""
        removed = 0
        with self._lock:
            active = _segment_name(self._active_first)
            for _, name in _list_segments(self.path):
                seal = self._sealed.get(name)
                if name == active or seal is None:
                    continue
                if seal["last_seq"] > watermark:
                    continue
                os.unlink(os.path.join(self.path, name))
                removed += 1
                del self._sealed[name]
                if failpoints is not None:
                    # Crash point: segment gone, manifest rewrite not
                    # yet landed — recovery drops the stale seal.
                    failpoints.fire("wal.gc.crash")
            if removed:
                self._write_manifest_locked()
        if removed:
            log.info("wal: GC removed %d segment(s) at watermark %d",
                     removed, watermark)
        return removed

    # -- introspection / lifecycle -------------------------------------------

    @property
    def last_seq(self) -> int:
        return self._seq

    @property
    def durable_seq(self) -> int:
        return self._durable_seq

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "last_seq": self._seq,
                "durable_seq": self._durable_seq,
                "segments": len(_list_segments(self.path)),
                "sealed_segments": len(self._sealed),
                "torn_records": self.torn_records,
                "torn_bytes": self.torn_bytes,
            }

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            if self._file is not None:
                self._fsync_locked()
            self._closed = True
            self._cond.notify_all()
        if self._flusher is not None:
            self._flusher.join(timeout=5.0)
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def __enter__(self) -> "WriteAheadLog":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
