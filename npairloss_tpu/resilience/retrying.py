"""Retry with jittered exponential backoff — the one retry primitive.

Snapshot save/restore I/O and prefetch-worker respawn all retry through
``call_with_retry`` so the schedule (exponential growth, cap, full
decorrelated jitter) and the logging are defined exactly once.  The
clock and the randomness are injectable, so tests pin the schedule with
a fake ``sleep`` and a seeded ``rng`` instead of real waiting.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Any, Callable, Optional, Tuple, Type

log = logging.getLogger("npairloss_tpu.resilience")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff: attempt ``k`` (1-based) failing
    sleeps ``min(base_delay * multiplier**(k-1), max_delay)`` scaled by
    ``1 ± jitter`` before attempt ``k+1``; after ``max_attempts`` the
    last error propagates.

    ``retry_on`` bounds what counts as transient — everything else
    (a shape mismatch, a KeyboardInterrupt) propagates immediately.
    """

    max_attempts: int = 3
    base_delay: float = 0.5
    max_delay: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.25
    jitter_cap_s: Optional[float] = None
    retry_on: Tuple[Type[BaseException], ...] = (OSError,)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.jitter < 0 or self.jitter > 1:
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")
        if self.jitter_cap_s is not None and self.jitter_cap_s < 0:
            raise ValueError(
                f"jitter_cap_s must be >= 0, got {self.jitter_cap_s}")

    def delay(self, attempt: int,
              rng: Optional[random.Random] = None) -> float:
        """Backoff before the retry that follows failed attempt
        ``attempt`` (1-based).  ``jitter_cap_s`` bounds the ABSOLUTE
        jitter contribution: once the exponential base delay grows
        large, relative jitter stops scaling with it, so a fleet of
        late-attempt retriers still decorrelates without one unlucky
        draw doubling a 30s wait."""
        d = min(self.base_delay * self.multiplier ** (attempt - 1),
                self.max_delay)
        if self.jitter:
            u = (rng.random() if rng is not None else random.random())
            spread = self.jitter * d
            if self.jitter_cap_s is not None:
                spread = min(spread, self.jitter_cap_s)
            d += spread * (2.0 * u - 1.0)
        return max(d, 0.0)


# Named policies: call sites that retry for a *reason* declare it here
# once, so the schedule is reviewable in one place instead of scattered
# inline literals.  WAL replay re-reads whole segment files (cheap,
# must converge fast after a cold restart); segment open contends with
# the GC unlink window (short, capped jitter keeps the tail bounded).
_NAMED_POLICIES = {
    "wal_replay": RetryPolicy(max_attempts=4, base_delay=0.05,
                              max_delay=1.0, jitter=0.5,
                              jitter_cap_s=0.2),
    "wal_segment_open": RetryPolicy(max_attempts=3, base_delay=0.02,
                                    max_delay=0.5, jitter=0.5,
                                    jitter_cap_s=0.1),
}


def named_policy(name: str) -> RetryPolicy:
    """The registered :class:`RetryPolicy` for ``name``; KeyError with
    the known names when the name is not registered (a typo'd policy
    name must fail loudly, not fall back to defaults)."""
    try:
        return _NAMED_POLICIES[name]
    except KeyError:
        raise KeyError(
            f"unknown retry policy {name!r} — known: "
            f"{sorted(_NAMED_POLICIES)}") from None


def call_with_retry(
    fn: Callable[[], Any],
    policy: Optional[RetryPolicy] = None,
    *,
    describe: str = "operation",
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
    on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
) -> Any:
    """Run ``fn`` under ``policy``; returns its result or re-raises the
    final error.  ``on_retry(attempt, delay_s, exc)`` fires before each
    backoff sleep (telemetry hook)."""
    policy = policy if policy is not None else RetryPolicy()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except policy.retry_on as e:
            if attempt >= policy.max_attempts:
                raise
            d = policy.delay(attempt, rng)
            log.warning(
                "%s failed (attempt %d/%d): %s — retrying in %.2fs",
                describe, attempt, policy.max_attempts, e, d,
            )
            if on_retry is not None:
                on_retry(attempt, d, e)
            sleep(d)
