"""Retry with jittered exponential backoff — the one retry primitive.

Snapshot save/restore I/O and prefetch-worker respawn all retry through
``call_with_retry`` so the schedule (exponential growth, cap, full
decorrelated jitter) and the logging are defined exactly once.  The
clock and the randomness are injectable, so tests pin the schedule with
a fake ``sleep`` and a seeded ``rng`` instead of real waiting.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Any, Callable, Optional, Tuple, Type

log = logging.getLogger("npairloss_tpu.resilience")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff: attempt ``k`` (1-based) failing
    sleeps ``min(base_delay * multiplier**(k-1), max_delay)`` scaled by
    ``1 ± jitter`` before attempt ``k+1``; after ``max_attempts`` the
    last error propagates.

    ``retry_on`` bounds what counts as transient — everything else
    (a shape mismatch, a KeyboardInterrupt) propagates immediately.
    """

    max_attempts: int = 3
    base_delay: float = 0.5
    max_delay: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.25
    retry_on: Tuple[Type[BaseException], ...] = (OSError,)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.jitter < 0 or self.jitter > 1:
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")

    def delay(self, attempt: int,
              rng: Optional[random.Random] = None) -> float:
        """Backoff before the retry that follows failed attempt
        ``attempt`` (1-based)."""
        d = min(self.base_delay * self.multiplier ** (attempt - 1),
                self.max_delay)
        if self.jitter:
            u = (rng.random() if rng is not None else random.random())
            d *= 1.0 + self.jitter * (2.0 * u - 1.0)
        return max(d, 0.0)


def call_with_retry(
    fn: Callable[[], Any],
    policy: Optional[RetryPolicy] = None,
    *,
    describe: str = "operation",
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
    on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
) -> Any:
    """Run ``fn`` under ``policy``; returns its result or re-raises the
    final error.  ``on_retry(attempt, delay_s, exc)`` fires before each
    backoff sleep (telemetry hook)."""
    policy = policy if policy is not None else RetryPolicy()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except policy.retry_on as e:
            if attempt >= policy.max_attempts:
                raise
            d = policy.delay(attempt, rng)
            log.warning(
                "%s failed (attempt %d/%d): %s — retrying in %.2fs",
                describe, attempt, policy.max_attempts, e, d,
            )
            if on_retry is not None:
                on_retry(attempt, d, e)
            sleep(d)
