"""Atomic snapshot commit, validation, and retention GC.

The commit protocol (docs/RESILIENCE.md):

  1. write the Orbax checkpoint into ``<final>.tmp-<pid>-<nonce>``
     (retried under the caller's :class:`~.retrying.RetryPolicy` —
     transient I/O must not abort a run);
  2. wait for the async save to land, then write ``manifest.json``
     inside the tmp dir: format tag, the solver step, and a per-array
     CRC-32 + shape/dtype record for every leaf of the state tree
     (written via its own write-fsync-rename so the manifest itself can
     never be torn);
  3. fsync and ``os.replace`` the tmp dir onto the final name.

The rename is the commit point: a snapshot either exists at its final
name complete-with-manifest, or it does not exist at all.  A crash at
any earlier point leaves only a ``.tmp-`` dir, which the resume scan
never matches; a snapshot that *is* at its final name but fails
manifest validation (bit rot, a partial copy, an injected
``snapshot.commit.torn``) is detected by checksum and skipped.

Validation is two-phase because recomputing checksums requires the
array bytes: :func:`validate_snapshot` is the cheap structural check
(manifest present, parses, right format), and :func:`verify_restored`
compares the restored tree's checksums against the manifest after an
Orbax restore.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from npairloss_tpu.resilience import failpoints
from npairloss_tpu.resilience.retrying import RetryPolicy, call_with_retry

log = logging.getLogger("npairloss_tpu.resilience")

MANIFEST_NAME = "manifest.json"
SNAPSHOT_FORMAT = "npairloss-snapshot-v1"
TMP_MARKER = ".tmp-"
QUARANTINE_SUFFIX = ".quarantined"
# Solver.snapshot_path naming: <prefix>iter_<step>.ckpt
_STEP_RE = r"iter_(\d+)\.ckpt"


class SnapshotError(RuntimeError):
    """A snapshot could not be committed or restored."""


class SnapshotValidationError(SnapshotError):
    """A snapshot on disk is torn/corrupt (failed manifest validation)."""


# -- checksums ------------------------------------------------------------


def _leaf_items(tree: Any) -> List[Tuple[str, Any]]:
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in leaves]


def state_checksums(tree: Any) -> Dict[str, Dict[str, Any]]:
    """Per-leaf CRC-32 + shape/dtype over the host bytes of ``tree``.

    CRC-32 (not a cryptographic hash): the threat model is torn writes
    and bit rot, not tampering, and crc32 streams at memory bandwidth.
    """
    out: Dict[str, Dict[str, Any]] = {}
    for key, leaf in _leaf_items(tree):
        a = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
        out[key] = {
            "crc32": zlib.crc32(a.tobytes()) & 0xFFFFFFFF,
            "shape": list(a.shape),
            "dtype": str(a.dtype),
        }
    return out


def verify_restored(tree: Any, manifest: Dict[str, Any]) -> None:
    """Compare a restored state tree against its manifest; raises
    :class:`SnapshotValidationError` naming the first mismatches."""
    want = manifest.get("arrays", {})
    got = state_checksums(tree)
    if set(want) != set(got):
        missing = sorted(set(want) - set(got))[:3]
        extra = sorted(set(got) - set(want))[:3]
        raise SnapshotValidationError(
            f"array set mismatch (missing={missing}, unexpected={extra})"
        )
    bad = [k for k in want if want[k]["crc32"] != got[k]["crc32"]]
    if bad:
        raise SnapshotValidationError(
            f"checksum mismatch on {len(bad)} array(s), "
            f"e.g. {sorted(bad)[:3]}"
        )


# -- manifest -------------------------------------------------------------


def _fsync_dir(path: str) -> None:
    # Directory fsync makes the rename durable; best-effort because not
    # every filesystem supports it (and a lost-on-power-cut snapshot is
    # exactly what the validator + older snapshots exist to absorb).
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_manifest(snapshot_dir: str, step: int,
                   checksums: Dict[str, Dict[str, Any]],
                   extra: Optional[Dict[str, Any]] = None) -> str:
    """Write ``manifest.json`` into ``snapshot_dir`` atomically
    (tmp file + fsync + rename + dir fsync)."""
    manifest = {
        "format": SNAPSHOT_FORMAT,
        "step": int(step),
        "created": time.time(),
        "arrays": checksums,
    }
    if extra:
        manifest.update(extra)
    path = os.path.join(snapshot_dir, MANIFEST_NAME)
    tmp = path + ".part"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # The rename made the manifest's CONTENT durable but not its NAME:
    # until the directory entry table is fsynced, a power cut can
    # resurrect the dir without manifest.json — the classic lost-rename
    # bug.  Syncing here also covers every other entry already in
    # ``snapshot_dir`` (the array files a commit wrote before us), so a
    # commit_snapshot tmp dir is fully durable before rename-publish.
    failpoints.fire("snapshot.commit.dirsync")
    _fsync_dir(snapshot_dir)
    return path


def read_manifest(snapshot_dir: str) -> Dict[str, Any]:
    with open(os.path.join(snapshot_dir, MANIFEST_NAME),
              encoding="utf-8") as f:
        return json.load(f)


def validate_snapshot(path: str) -> Dict[str, Any]:
    """Structural validation: committed dir with a parseable manifest of
    the right format.  Returns the manifest; raises
    :class:`SnapshotValidationError` with the reason otherwise."""
    if not os.path.isdir(path):
        raise SnapshotValidationError(f"not a snapshot directory: {path}")
    if TMP_MARKER in os.path.basename(path):
        raise SnapshotValidationError(f"uncommitted tmp snapshot: {path}")
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        raise SnapshotValidationError(
            "no manifest.json (torn commit, or a pre-resilience snapshot)"
        )
    try:
        manifest = read_manifest(path)
    except (OSError, ValueError) as e:
        raise SnapshotValidationError(f"unreadable manifest: {e}") from e
    if manifest.get("format") != SNAPSHOT_FORMAT:
        raise SnapshotValidationError(
            f"unknown manifest format {manifest.get('format')!r}"
        )
    if not isinstance(manifest.get("step"), int):
        raise SnapshotValidationError("manifest carries no integer step")
    if not isinstance(manifest.get("arrays"), dict):
        raise SnapshotValidationError("manifest carries no array records")
    return manifest


def validate_snapshot_wait(path: str, policy=None) -> Dict[str, Any]:
    """:func:`validate_snapshot` with the shared retry/backoff — the
    NON-rank-0 side of a multi-controller resume (docs/DISTRIBUTED.md).

    The multihost save contract is: every rank enters the collective
    Orbax save, then rank 0 alone writes ``manifest.json``.  A
    relaunched non-zero rank scanning ``--resume auto`` can therefore
    see the committed Orbax dir BEFORE rank 0's manifest lands and
    would mis-read a perfectly valid snapshot as torn — so it waits on
    the manifest (bounded, jittered backoff) instead of skipping.
    Rank 0 never calls this: on rank 0 a missing manifest really is a
    torn commit.
    """
    from npairloss_tpu.resilience.retrying import RetryPolicy, call_with_retry

    policy = policy if policy is not None else RetryPolicy()
    import dataclasses as _dc

    # Same schedule as snapshot I/O, but the transient here is the
    # manifest race (surfaced as SnapshotValidationError), not an
    # OSError — widen retry_on for this call only.
    policy = _dc.replace(
        policy,
        retry_on=tuple(set(policy.retry_on) | {SnapshotValidationError}),
    )
    return call_with_retry(
        lambda: validate_snapshot(path), policy,
        describe=f"manifest wait ({path})",
    )


# -- commit ---------------------------------------------------------------


def commit_snapshot(
    checkpointer,
    final_path: str,
    state: Any,
    step: int,
    *,
    policy: Optional[RetryPolicy] = None,
    on_retry=None,
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Write ``state`` as a committed snapshot at ``final_path``.

    ``checkpointer`` is an Orbax ``StandardCheckpointer`` (or anything
    with ``save(path, state, force=) -> None`` + ``wait_until_finished``).
    Returns ``final_path``; on failure nothing exists at ``final_path``
    (a ``.tmp-`` dir may be left for post-mortem and is ignored by the
    resume scan; the next commit attempt reuses its own fresh nonce).
    """
    final_path = os.path.abspath(final_path)
    parent = os.path.dirname(final_path)
    os.makedirs(parent, exist_ok=True)
    tmp = (f"{final_path}{TMP_MARKER}{os.getpid()}-"
           f"{os.urandom(2).hex()}")

    def do_save():
        failpoints.fire("snapshot.save.io")
        checkpointer.save(tmp, state, force=True)
        checkpointer.wait_until_finished()

    call_with_retry(
        do_save, policy, describe=f"snapshot save ({final_path})",
        on_retry=on_retry,
    )
    checks = state_checksums(state)
    if failpoints.should_fire("snapshot.commit.torn"):
        # Deterministic "torn snapshot": commit with poisoned
        # checksums so the resume validator must catch and skip it.
        for rec in checks.values():
            rec["crc32"] = (rec["crc32"] + 1) & 0xFFFFFFFF
    write_manifest(tmp, step, checks, extra=extra)
    # On any failure up to here the tmp dir never reached its final
    # name: the run sees the error, the resume scan never sees the dir
    # (it is left for post-mortem; retention GC sweeps stale ones).
    failpoints.fire("snapshot.commit.crash")
    if os.path.isdir(final_path):
        # Re-committing the same step (emergency snapshot on a cadence
        # boundary): the rename target must not exist.
        shutil.rmtree(final_path)
    os.replace(tmp, final_path)
    _fsync_dir(parent)
    return final_path


# -- discovery + GC -------------------------------------------------------


def list_snapshots(snapshot_prefix: str) -> List[Tuple[int, str]]:
    """Committed snapshot candidates for a ``snapshot_prefix``, as
    ``(step, path)`` sorted by step ascending.  Tmp dirs never match."""
    prefix = os.path.abspath(snapshot_prefix)
    parent, base = os.path.dirname(prefix), os.path.basename(prefix)
    pat = re.compile(re.escape(base) + _STEP_RE + r"$")
    out: List[Tuple[int, str]] = []
    try:
        entries = os.listdir(parent)
    except OSError:
        return out
    for name in entries:
        m = pat.match(name)
        path = os.path.join(parent, name)
        if m and os.path.isdir(path):
            out.append((int(m.group(1)), path))
    out.sort()
    return out


def gc_snapshots(snapshot_prefix: str, max_keep: int) -> List[str]:
    """Retention GC: delete committed snapshots beyond the newest
    ``max_keep`` (``max_keep <= 0`` keeps every committed snapshot),
    then ALWAYS sweep stale ``.tmp-`` debris from failed commits and
    ``.quarantined`` dirs a past rollback deemed poisoned — those are
    full-checkpoint-sized and reclaimable regardless of the retention
    setting.  Best-effort: a dir that refuses to delete is logged and
    left, never fatal.  Safe single-writer assumption: GC runs right
    after a successful commit in the saving process, so no save is in
    flight."""
    deleted: List[str] = []
    if max_keep > 0:
        snaps = list_snapshots(snapshot_prefix)
        for step, path in snaps[:-max_keep] if len(snaps) > max_keep else []:
            try:
                shutil.rmtree(path)
                deleted.append(path)
                log.info("snapshot GC: removed iter-%d (%s)", step, path)
            except OSError as e:
                log.warning("snapshot GC: could not remove %s: %s", path, e)
    prefix = os.path.abspath(snapshot_prefix)
    parent, base = os.path.dirname(prefix), os.path.basename(prefix)
    try:
        entries = os.listdir(parent)
    except OSError:
        return deleted
    for name in entries:
        if name.startswith(base) and (
            TMP_MARKER in name or name.endswith(QUARANTINE_SUFFIX)
        ):
            path = os.path.join(parent, name)
            try:
                shutil.rmtree(path)
                deleted.append(path)
                log.info("snapshot GC: removed stale %s", path)
            except OSError as e:
                log.warning("snapshot GC: could not remove %s: %s", path, e)
    return deleted


def quarantine_snapshots(snapshot_prefix: str, min_step: int) -> List[str]:
    """Rename committed snapshots with step > ``min_step`` out of the
    resume scan's namespace (``<dir>.quarantined``) — used by divergence
    rollback, which has just judged them poisoned: their bytes are
    checksum-valid, so without the rename a later crash + ``--resume
    auto`` would restore NaN-era params and dive straight back into
    divergence.  The rename keeps them on disk for post-mortem; GC
    reclaims them."""
    out: List[str] = []
    for step, path in list_snapshots(snapshot_prefix):
        if step <= min_step:
            continue
        target = path + QUARANTINE_SUFFIX
        try:
            if os.path.isdir(target):
                shutil.rmtree(target)
            os.rename(path, target)
            out.append(target)
            log.warning("quarantined suspect snapshot iter-%d -> %s",
                        step, target)
        except OSError as e:
            log.warning("could not quarantine %s: %s", path, e)
    return out
