"""Divergence guard: N consecutive non-finite losses -> rollback or halt.

A diverged run on a pod burns accelerator-days producing NaNs; the
reference had no numeric checks at all (SURVEY.md §5.2).  The guard
watches the per-step loss on host (the one extra sync it costs is the
reason it is opt-in) and, once ``patience`` consecutive steps are
non-finite, either halts with a diagnosis or rolls the Solver back to
the newest *valid* snapshot — optionally scaling the base lr down so
the trajectory does not march straight back into the same cliff.
Rollbacks are bounded (``max_rollbacks``); past the bound the guard
halts, because an endlessly rolling-back run is an outage that looks
like progress.

Complements ``obs.health`` (PR 2): health signals *show* the explosion
coming; the guard *survives* it.

Pipelined mode (``SolverConfig.pipeline``, docs/PIPELINE.md) removes
the per-step sync: the jitted step carries an in-graph consecutive-
non-finite counter, and the host replays the window's losses through
``observe`` only at window-boundary reads — same trip step, same
rollback, detected up to one window late (bounded staleness).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

ACTIONS = ("rollback", "halt")


class DivergenceError(RuntimeError):
    """Training diverged and could not (or was configured not to) recover."""


@dataclasses.dataclass(frozen=True)
class DivergenceConfig:
    """``patience`` consecutive non-finite losses trip the guard.

    ``action="rollback"`` restores the newest valid snapshot (fresh
    optimizer trajectory from iteration k) and multiplies ``base_lr``
    by ``lr_scale``; ``action="halt"`` raises :class:`DivergenceError`
    immediately — the diagnostic stop for runs where silent recovery
    would mask a real bug.
    """

    patience: int = 3
    action: str = "rollback"
    lr_scale: float = 1.0
    max_rollbacks: int = 2

    def __post_init__(self):
        if self.patience < 1:
            raise ValueError(f"patience must be >= 1, got {self.patience}")
        if self.action not in ACTIONS:
            raise ValueError(
                f"action must be one of {ACTIONS}, got {self.action!r}"
            )
        if not (0.0 < self.lr_scale <= 1.0):
            raise ValueError(
                f"lr_scale must be in (0, 1], got {self.lr_scale}"
            )


@dataclasses.dataclass(frozen=True)
class RollbackRequest:
    """An externally REQUESTED rollback — the divergence guard's
    recovery generalized to health-signal triggers (the alert→actuation
    control plane, docs/RESILIENCE.md §Remediation).

    The non-finite guard trips in-loop on its own streak; a health
    alert (embedding collapse) trips OUT of loop, on the live-obs tick
    thread, so the actuator sets a request the train loop executes at
    its next safe point.  ``before_wall_time`` (the alert's
    ``fired_at``) restricts the restore to snapshots COMMITTED before
    the incident started — a snapshot captured mid-collapse is not a
    recovery target; ``lr_scale`` optionally damps the relaunch the way
    the divergence rollback does.
    """

    reason: str
    before_wall_time: Optional[float] = None
    lr_scale: float = 1.0

    def __post_init__(self):
        if not (0.0 < self.lr_scale <= 1.0):
            raise ValueError(
                f"lr_scale must be in (0, 1], got {self.lr_scale}"
            )


class DivergenceGuard:
    """Host-side streak tracker; the Solver owns the recovery action."""

    def __init__(self, cfg: DivergenceConfig):
        self.cfg = cfg
        self.streak = 0
        self.rollbacks = 0

    def observe(self, loss: float) -> bool:
        """Feed one step's loss; True when the guard trips."""
        if math.isfinite(loss):
            self.streak = 0
            return False
        self.streak += 1
        return self.streak >= self.cfg.patience
