"""Graceful preemption: SIGTERM/SIGINT -> finish the step, snapshot, exit.

At pod scale preemptions and maintenance events are routine, not
exceptional: the difference between losing ``snapshot`` iterations and
losing none is catching the signal, finishing the in-flight step,
committing an emergency snapshot, and exiting with a code the
supervisor understands (:data:`EXIT_PREEMPTED`, BSD ``EX_TEMPFAIL`` —
"transient, relaunch me") so it relaunches with ``--resume auto``.

:class:`PreemptionSignal` is the sticky flag between the async signal
world and the synchronous train loop: handlers only set an event; the
Solver polls ``requested`` once per step and does the actual work on
its own thread.  A second Ctrl-C escalates to the normal
``KeyboardInterrupt`` so an operator can still hard-kill a wedged run.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Iterable, Optional

log = logging.getLogger("npairloss_tpu.resilience")

# BSD sysexits EX_TEMPFAIL: transient failure, safe to relaunch.  The
# supervisor contract (docs/RESILIENCE.md): rc == EXIT_PREEMPTED means
# "relaunch with --resume auto"; rc == 0 means done; anything else is a
# real error.
EXIT_PREEMPTED = 75


class TrainingPreempted(RuntimeError):
    """Raised by ``Solver.train`` after the emergency snapshot landed."""

    def __init__(self, step: int, snapshot_path: Optional[str] = None,
                 signum: Optional[int] = None):
        name = signal.Signals(signum).name if signum is not None else "request"
        super().__init__(
            f"training preempted by {name} at iteration {step}"
            + (f" (snapshot: {snapshot_path})" if snapshot_path else "")
        )
        self.step = step
        self.snapshot_path = snapshot_path
        self.signum = signum


class PreemptionSignal:
    """Sticky stop-after-this-step flag, settable from a signal handler
    or programmatically (``request()``).

    Use as a context manager around training to install/restore the
    handlers; ``install`` is a no-op off the main thread (CPython only
    allows signal handlers there), so embedded/threaded callers can
    still drive ``request()`` by hand.
    """

    def __init__(self,
                 signals: Iterable[int] = (signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._prev: dict = {}
        self.signum: Optional[int] = None

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def request(self, signum: Optional[int] = None) -> None:
        self.signum = signum
        self._event.set()

    def _handler(self, signum, frame):
        if self._event.is_set() and signum == signal.SIGINT:
            # Second Ctrl-C: the operator wants out NOW.
            raise KeyboardInterrupt
        log.warning(
            "received %s — will snapshot and exit after the in-flight step",
            signal.Signals(signum).name,
        )
        self.request(signum)

    def install(self) -> "PreemptionSignal":
        if threading.current_thread() is not threading.main_thread():
            log.warning(
                "PreemptionSignal.install skipped: signal handlers are "
                "main-thread-only (use .request() to stop programmatically)"
            )
            return self
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handler)
        return self

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):  # interpreter teardown
                pass
        self._prev.clear()

    def __enter__(self) -> "PreemptionSignal":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
