"""Pass ``markers`` — tier-1 stays under budget mechanically.

Tier-1 (``pytest -m 'not slow'``) has an 870 s budget (ROADMAP.md)
kept by hand: when a test grows past a few seconds somebody notices
in review — or nobody does, and the suite grazes the timeout like the
pre-PR-7 862 s run.  This pass makes it mechanical: a committed timing
history (``tests/timing_history.json``, regenerated from any tier-1
run's ``--durations=0`` output via ``staticcheck --update-timings``)
says what each test actually costs; any test at or over the threshold
must either carry ``@pytest.mark.slow`` (module-level ``pytestmark``
counts) or a ``# slow-ok: <reason>`` comment on its ``def`` line (a
deliberately-kept tier-1 heavyweight, e.g. one that smoke-covers a
path ci.sh cannot).

No history file -> the pass is skipped with a note (a fresh clone
must not fail on data it cannot have).  A history entry whose test no
longer exists is ignored (renames are not findings).

Stdlib-only and self-contained (the bench_check file-path-load
contract, docs/STATICCHECK.md).
"""

from __future__ import annotations

import ast
import json
import re
from typing import Dict, List, Optional, Tuple

from npairloss_tpu.analysis.findings import Finding
from npairloss_tpu.analysis.tree import SourceTree

PASS_NAME = "markers"

HISTORY_PATH = "tests/timing_history.json"
DEFAULT_THRESHOLD_S = 10.0
SLOW_OK = "slow-ok"

_DURATION_LINE_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(?:call|setup|teardown)\s+(\S+)")


def parse_durations_log(text: str) -> Dict[str, float]:
    """{nodeid -> seconds} from ``pytest --durations=0`` output (call
    phase dominates; phases of one nodeid are summed)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        m = _DURATION_LINE_RE.match(line)
        if m:
            nodeid = m.group(2)
            out[nodeid] = out.get(nodeid, 0.0) + float(m.group(1))
    return out


def load_history(tree: SourceTree) -> Optional[Dict]:
    text = tree.text(HISTORY_PATH)
    if text is None:
        return None
    try:
        obj = json.loads(text)
    except ValueError:
        return {"_error": f"{HISTORY_PATH} is not valid JSON"}
    if not isinstance(obj, dict) or \
            not isinstance(obj.get("durations"), dict):
        return {"_error": f"{HISTORY_PATH} lacks a 'durations' object"}
    return obj


def _split_nodeid(nodeid: str) -> Optional[Tuple[str, str]]:
    """(file, function) from ``tests/test_x.py::Class::test_y[param]``."""
    parts = nodeid.split("::")
    if len(parts) < 2 or not parts[0].endswith(".py"):
        return None
    func = parts[-1].split("[", 1)[0]
    return parts[0].replace("\\", "/"), func


def _marks_slow(dec: ast.AST) -> bool:
    """True for ``pytest.mark.slow`` / ``pytest.mark.slow(...)``."""
    if isinstance(dec, ast.Call):
        dec = dec.func
    return isinstance(dec, ast.Attribute) and dec.attr == "slow"


def _module_marks_slow(mod: ast.Module) -> bool:
    for stmt in mod.body:
        if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "pytestmark"
                for t in stmt.targets):
            vals = stmt.value.elts if isinstance(
                stmt.value, (ast.List, ast.Tuple)) else [stmt.value]
            if any(_marks_slow(v) for v in vals):
                return True
    return False


def _find_test_fn(mod: ast.Module, name: str) -> Optional[ast.FunctionDef]:
    for node in ast.walk(mod):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def run(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []
    history = load_history(tree)
    if history is None:
        return findings  # no data: skipped (runner notes it)
    if "_error" in history:
        findings.append(Finding(
            PASS_NAME, HISTORY_PATH, 0, "history",
            history["_error"]))
        return findings
    threshold = float(history.get("threshold_s", DEFAULT_THRESHOLD_S))

    # Aggregate parametrized nodeids to their function's worst case.
    worst: Dict[Tuple[str, str], float] = {}
    for nodeid, secs in history["durations"].items():
        if not isinstance(secs, (int, float)):
            continue
        loc = _split_nodeid(str(nodeid))
        if loc is None:
            continue
        worst[loc] = max(worst.get(loc, 0.0), float(secs))

    for (rel, func), secs in sorted(worst.items()):
        if secs < threshold or not tree.exists(rel):
            continue
        mod = tree.parse(rel)
        if mod is None:
            continue
        fn = _find_test_fn(mod, func)
        if fn is None:
            continue  # renamed/removed since the history was taken
        if _module_marks_slow(mod) or any(
                _marks_slow(d) for d in fn.decorator_list):
            continue
        note = tree.comments(rel).get(fn.lineno, "")
        if note.startswith(SLOW_OK):
            continue
        findings.append(Finding(
            PASS_NAME, rel, fn.lineno, func,
            f"{func} took {secs:.1f}s in the recorded tier-1 run "
            f"(threshold {threshold:g}s) without @pytest.mark.slow — "
            "mark it slow (+ a ci.sh smoke if it guards a path), or "
            f"annotate '# {SLOW_OK}: <reason>' on the def line to "
            "keep it in tier-1 deliberately"))
    return findings
