"""Pass ``scopes`` — every collective call site carries a comm marker.

The fleet observatory's runtime gate (``bench_check --fleet-report``,
docs/OBSERVABILITY.md §Fleet) refuses a run with unattributed
collective bytes — but it needs a multi-rank run to fire, and a kind
that carries an analytic *claim* (the grad-sync allreduce) can absorb
an uninstrumented collective's bytes without tripping it at all.  This
pass is the static twin: every ``jax.lax`` collective call must be
*lexically* enclosed in a ``jax.named_scope("comm/<kind>")`` block, so
an uninstrumented new exchange path fails CI on a CPU box in
milliseconds instead of surviving until a pod run's reconciliation.

Escape hatch: a ``# comm-scope-ok: <reason>`` comment on the call line
tolerates a site the scope rule genuinely cannot serve (document why).

Stdlib-only and self-contained (the bench_check file-path-load
contract, docs/STATICCHECK.md).
"""

from __future__ import annotations

import ast
from typing import List, Set

from npairloss_tpu.analysis.findings import Finding
from npairloss_tpu.analysis.tree import SourceTree, const_str, dotted_name

PASS_NAME = "scopes"

# The jax.lax primitives that move bytes across the mesh.  axis_index
# and axis_size are mesh *queries*, not exchanges — excluded.
COLLECTIVES = frozenset({
    "all_gather", "all_to_all", "ppermute", "pshuffle",
    "psum", "psum_scatter", "pmean", "pmax", "pmin",
})

COMM_PREFIX = "comm/"
ANNOTATION = "comm-scope-ok"

# Callables that open a named scope (``utils.profiling.annotate`` is
# ``jax.named_scope`` re-exported).
_SCOPE_FNS = {"named_scope", "annotate"}


def _is_collective_call(node: ast.Call) -> str:
    """The collective's name when ``node`` calls one (``jax.lax.psum``
    / ``lax.psum`` attribute chains), else ''."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in COLLECTIVES:
        base = dotted_name(fn.value)
        if base is not None and (base == "lax" or base.endswith(".lax")):
            return fn.attr
    return ""


def _opens_comm_scope(item: ast.withitem) -> bool:
    ctx = item.context_expr
    if not isinstance(ctx, ast.Call):
        return False
    fn = ctx.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if name not in _SCOPE_FNS or not ctx.args:
        return False
    lit = const_str(ctx.args[0])
    return bool(lit and lit.startswith(COMM_PREFIX))


def _lax_from_imports(tree_mod: ast.Module) -> Set[str]:
    """Names bound by ``from jax.lax import psum, ...`` — bare-name
    collective calls."""
    out: Set[str] = set()
    for node in ast.walk(tree_mod):
        if isinstance(node, ast.ImportFrom) and node.module == "jax.lax":
            for alias in node.names:
                if alias.name in COLLECTIVES:
                    out.add(alias.asname or alias.name)
    return out


def run(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []
    for rel in tree.py_files(subdirs=("npairloss_tpu",)):
        mod = tree.parse(rel)
        if mod is None:
            continue
        bare = _lax_from_imports(mod)
        comments = tree.comments(rel)

        def visit(node: ast.AST, in_comm: bool, rel=rel,
                  bare=bare, comments=comments) -> None:
            if isinstance(node, ast.With):
                entered = in_comm or any(
                    _opens_comm_scope(i) for i in node.items)
                for item in node.items:
                    visit(item, in_comm)
                for child in node.body:
                    visit(child, entered)
                return
            if isinstance(node, ast.Call):
                name = _is_collective_call(node)
                if not name and isinstance(node.func, ast.Name) \
                        and node.func.id in bare:
                    name = node.func.id
                if name and not in_comm:
                    note = comments.get(node.lineno, "")
                    if not note.startswith(ANNOTATION):
                        findings.append(Finding(
                            PASS_NAME, rel, node.lineno, name,
                            f"jax.lax.{name} call not lexically "
                            f"enclosed in a jax.named_scope("
                            f"'{COMM_PREFIX}<kind>') block — its bytes "
                            "would be unattributed (or silently absorbed "
                            "by an analytic claim) in the fleet comms "
                            "reconciliation; wrap the exchange or "
                            f"annotate '# {ANNOTATION}: <reason>'"))
            for child in ast.iter_child_nodes(node):
                visit(child, in_comm)

        visit(mod, False)
    return findings
