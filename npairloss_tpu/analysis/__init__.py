"""npairloss_tpu.analysis — the repo-wide invariant linter (staticcheck).

An AST-based static-analysis suite (stdlib ``ast`` + an import-graph
walker, itself jax-free) that enforces at lint time the contracts the
runtime gates can only catch after the fact — often only on hardware
CI does not have (docs/STATICCHECK.md):

  * ``purity``     — transitive jax-free proof for the file-path-loaded
                     contract modules, with a loud opt-in table;
  * ``scopes``     — every ``jax.lax`` collective lexically inside a
                     ``comm/<kind>`` named_scope (the static twin of
                     the fleet observatory's zero-unattributed-bytes
                     runtime gate);
  * ``locks``      — ``# guarded-by:`` mutation discipline on shared
                     state (MetricRegistry, SLOEvaluator,
                     RemediationEngine, RetrievalServer swap state);
  * ``contracts``  — versioned ``npairloss-*-v1`` writer/validator
                     pairing, key twins, writer pins;
  * ``vocab``      — failpoints / CLI flags / choice pins / watchdog
                     names match their documented tables;
  * ``markers``    — tier-1 timing history vs ``@pytest.mark.slow``.

Every module here is stdlib-only and self-contained enough for
``scripts/bench_check.py --static`` to file-path-load the chain from a
jax-free process — the same contract as ``obs.live.alerts``, and the
first thing the ``purity`` pass proves about this very package.
"""

from npairloss_tpu.analysis.findings import Finding
from npairloss_tpu.analysis.report import (
    STATICCHECK_SCHEMA,
    build_report,
    load_report,
    validate_staticcheck_report,
    write_report,
)
from npairloss_tpu.analysis.runner import (
    PASS_NAMES,
    load_allowlist,
    run_suite,
)

__all__ = [
    "Finding",
    "STATICCHECK_SCHEMA",
    "PASS_NAMES",
    "build_report",
    "load_report",
    "validate_staticcheck_report",
    "write_report",
    "load_allowlist",
    "run_suite",
]
