"""Pass ``purity`` — transitive jax-free proof for the contract modules.

A handful of modules are *file-path-loaded* by jax-free processes
(``scripts/bench_check.py`` gates, ``bench.py``'s parent): their
contract is that executing them imports NO heavy dependency — not
directly, not transitively.  Until now that contract was enforced only
by actually running the gates; this pass proves it at lint time by
walking the module-level import graph.

Semantics mirror the file-path-load mechanics (``sys.modules``
pre-seeding): an intra-repo import edge goes to the named module FILE,
never through parent-package ``__init__``s, and only *module-level*
imports count — an import inside a function body is lazy by
construction and deliberately tolerated (the ``aggregate.percentile``
pattern).  ``if TYPE_CHECKING:`` blocks never execute and are skipped.

The declared contract list is the allowlist: a file-path-load call
site (``spec_from_file_location("npairloss_tpu....")``) naming a module
NOT declared here is itself a finding — a new contract module must opt
in loudly, in this table, where the purity proof will cover it.

Stdlib-only and self-contained (the very contract it checks).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from npairloss_tpu.analysis.findings import Finding
from npairloss_tpu.analysis.tree import SourceTree, const_str

PASS_NAME = "purity"

# Top-level import names that end the jax-free proof.  numpy is heavy
# here: the contract modules are *stdlib-only* (their docstrings say
# so), and a gate that can hang on BLAS thread-pool init is a gate
# that can hang.
HEAVY_DEPS = frozenset({
    "jax", "jaxlib", "flax", "numpy", "scipy", "optax", "orbax",
    "tensorflow", "torch", "pandas", "ml_dtypes", "etils", "chex",
})

# The declared contract modules: root-relative path -> why it must stay
# jax-free.  Adding a file-path-load site for a module absent from this
# table is a finding (opt in HERE, loudly).
CONTRACT_MODULES: Dict[str, str] = {
    "npairloss_tpu/obs/sinks.py":
        "bench.py's jax-free parent file-path-loads it to append "
        "bench records",
    "npairloss_tpu/obs/fleet/stamp.py":
        "bench_check --fleet-report pre-seeds it for the aggregate "
        "loader",
    "npairloss_tpu/obs/fleet/aggregate.py":
        "bench_check --fleet-report file-path-loads the fleet-report "
        "validator",
    "npairloss_tpu/obs/live/alerts.py":
        "bench_check --alerts file-path-loads the alerts-v1 validator",
    "npairloss_tpu/resilience/remediate.py":
        "bench_check --remediation file-path-loads the remediation-v1 "
        "validator",
    "npairloss_tpu/obs/quality/report.py":
        "bench_check --quality file-path-loads the quality-v1 "
        "validator",
    "npairloss_tpu/gameday/verdict.py":
        "bench_check --gameday file-path-loads the gameday-v1 "
        "validator",
    "npairloss_tpu/obs/qtrace/report.py":
        "bench_check --qtrace file-path-loads the qtrace-v1 "
        "validator",
    "npairloss_tpu/resilience/wal.py":
        "bench_check --wal file-path-loads the wal-v1 validator",
    "npairloss_tpu/resilience/failpoints.py":
        "wal.py's fault-injection seam; rides along in the --wal "
        "loader chain",
    "npairloss_tpu/resilience/retrying.py":
        "wal.py's replay/segment-open retry policies; rides along in "
        "the --wal loader chain",
    "scripts/bench_check.py":
        "the CI gate itself — must never hang on a backend import",
    "scripts/check_no_print.py":
        "the lint gate runs before any environment setup",
}

# The analysis suite itself is contract code (bench_check --static
# file-path-loads the whole chain); every analysis/*.py is implicitly
# declared.
ANALYSIS_DIR = "npairloss_tpu/analysis"

_DOTTED_RE = re.compile(r"^npairloss_tpu(\.[A-Za-z_][A-Za-z_0-9]*)+$")


def _is_type_checking_if(node: ast.If) -> bool:
    t = node.test
    if isinstance(t, ast.Name) and t.id == "TYPE_CHECKING":
        return True
    return (isinstance(t, ast.Attribute) and t.attr == "TYPE_CHECKING")


def _module_level_imports(tree: ast.Module) -> Iterator[ast.stmt]:
    """Import statements that execute at import time: module body,
    top-level try/if bodies (minus TYPE_CHECKING), and class bodies."""

    def visit(stmts) -> Iterator[ast.stmt]:
        for stmt in stmts:
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                yield stmt
            elif isinstance(stmt, ast.If):
                if _is_type_checking_if(stmt):
                    yield from visit(stmt.orelse)
                else:
                    yield from visit(stmt.body)
                    yield from visit(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                yield from visit(stmt.body)
                for h in stmt.handlers:
                    yield from visit(h.body)
                yield from visit(stmt.orelse)
                yield from visit(stmt.finalbody)
            elif isinstance(stmt, ast.ClassDef):
                yield from visit(stmt.body)

    yield from visit(tree.body)


def _rel_module_path(tree: SourceTree, dotted: str) -> Optional[str]:
    """Root-relative file for an intra-repo dotted module name."""
    base = dotted.replace(".", "/")
    for cand in (base + ".py", base + "/__init__.py"):
        if tree.exists(cand):
            return cand
    return None


def _package_of(rel: str) -> str:
    """Dotted package containing the module at ``rel``."""
    parts = rel.rsplit("/", 1)[0].split("/")
    return ".".join(parts)


def _edges(tree: SourceTree, rel: str) -> Iterator[Tuple[str, int, object]]:
    """(top_level_name_or_None, line, resolved_rel_or_None) per
    module-level import edge of ``rel``."""
    mod = tree.parse(rel)
    if mod is None:
        return
    for stmt in _module_level_imports(mod):
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                name = alias.name
                resolved = _rel_module_path(tree, name) \
                    if name.split(".")[0] == "npairloss_tpu" else None
                yield name.split(".")[0], stmt.lineno, resolved
        else:  # ImportFrom
            if stmt.level:  # relative import
                pkg_parts = _package_of(rel).split(".")
                if stmt.level > len(pkg_parts):
                    continue
                base = pkg_parts[:len(pkg_parts) - (stmt.level - 1)]
                name = ".".join(base + ([stmt.module]
                                        if stmt.module else []))
            else:
                name = stmt.module or ""
            top = name.split(".")[0] if name else None
            if top != "npairloss_tpu":
                if top:
                    yield top, stmt.lineno, None
                continue
            # from A.B import C: C may itself be a submodule
            for alias in stmt.names:
                sub = _rel_module_path(tree, f"{name}.{alias.name}")
                if sub is not None:
                    yield top, stmt.lineno, sub
                    continue
                resolved = _rel_module_path(tree, name)
                yield top, stmt.lineno, resolved


def _prove_pure(tree: SourceTree, start: str) -> Optional[Tuple[List[str], str, int]]:
    """BFS the import graph from ``start``; returns (chain, heavy_dep,
    line) on the first heavy reach, None when pure."""
    seen: Set[str] = {start}
    queue: List[Tuple[str, List[str]]] = [(start, [start])]
    while queue:
        rel, chain = queue.pop(0)
        for top, line, resolved in _edges(tree, rel):
            if top in HEAVY_DEPS:
                return chain, top, line
            if resolved is not None and resolved not in seen:
                seen.add(resolved)
                queue.append((resolved, chain + [resolved]))
    return None


def _file_path_load_sites(tree: SourceTree, rel: str
                          ) -> Iterator[Tuple[str, int]]:
    """(dotted_module, line) for every
    ``spec_from_file_location("npairloss_tpu....", ...)`` literal in
    ``rel`` — the loud-opt-in cross-check."""
    mod = tree.parse(rel)
    if mod is None:
        return
    for node in ast.walk(mod):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name != "spec_from_file_location" or not node.args:
            continue
        lit = const_str(node.args[0])
        if lit and _DOTTED_RE.match(lit):
            yield lit, node.lineno


# The chained-loader idiom (bench_check's _load_fleet_aggregate /
# _load_staticcheck) passes ("npairloss_tpu....", "file.py") tuples to
# a loop, so the dotted name never reaches spec_from_file_location as
# a literal — this textual scan catches those declarations too.
_TUPLE_SITE_RE = re.compile(
    r"[\"'](npairloss_tpu(?:\.[A-Za-z_][A-Za-z_0-9]*)+)[\"']\s*,\s*"
    r"[\"']([A-Za-z_0-9]+\.py)[\"']")


def run(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []
    declared = dict(CONTRACT_MODULES)
    for rel in tree.py_files():
        if rel.startswith(ANALYSIS_DIR + "/"):
            declared.setdefault(rel, "the staticcheck suite itself")

    # 1) every declared module present in this tree proves pure.
    for rel, why in sorted(declared.items()):
        if not tree.exists(rel):
            continue  # partial tree (fixtures); bench_check's own
            # loaders break loudly if a real contract file vanishes
        hit = _prove_pure(tree, rel)
        if hit is not None:
            chain, dep, line = hit
            via = " -> ".join(chain)
            findings.append(Finding(
                PASS_NAME, rel, line if len(chain) == 1 else 0,
                f"reaches-{dep}",
                f"contract module ({why}) transitively imports "
                f"{dep!r} at module level via {via} "
                f"(:{line} in {chain[-1]}) — jax-free file-path-load "
                "contract broken"))

    # 2) every file-path-load site names a declared module.
    declared_dotted = {
        rel[:-3].replace("/", ".").replace("scripts.", "")
        for rel in declared}
    declared_paths = set(declared)
    for rel in tree.py_files():
        seen_lits: Set[Tuple[str, int]] = set(
            _file_path_load_sites(tree, rel))
        text = tree.text(rel) or ""
        for m in _TUPLE_SITE_RE.finditer(text):
            line = text[:m.start()].count("\n") + 1
            seen_lits.add((m.group(1), line))
        for dotted, line in sorted(seen_lits):
            target = dotted.replace(".", "/") + ".py"
            if target in declared_paths or dotted in declared_dotted:
                continue
            if target.startswith(ANALYSIS_DIR + "/"):
                continue
            findings.append(Finding(
                PASS_NAME, rel, line, f"undeclared-{dotted}",
                f"file-path-loads {dotted!r} which is not declared in "
                "the purity contract table "
                "(analysis/purity.py CONTRACT_MODULES) — a new "
                "contract module must opt in loudly so the jax-free "
                "proof covers it"))
    return findings
