"""Pass ``vocab`` — code vocabularies match their documented tables.

Hand-maintained name sets drift silently: a failpoint registered in
code but absent from the RESILIENCE.md table is undriveable by anyone
reading the runbook; a CLI flag shown in a doc's command line but
renamed in argparse turns the runbook into a trap; the hardcoded
``_PRECISION_CHOICES`` in cli.py exists precisely because the parser
must stay jax-free, so only a pin can keep it honest against
``models.precision._POLICIES``.  This pass mechanizes each:

  * every failpoint name fired in the package appears in the
    RESILIENCE.md failpoint table, and vice versa;
  * every ``--flag`` in a documented command line that invokes one of
    OUR entry points exists in that tool's argparse (and the
    subcommand itself exists);
  * declared literal choice pins (cli ``_PRECISION_CHOICES`` vs the
    precision policy registry keys) are equal;
  * every watchdog preset ``name=`` in obs/live/watchdogs.py appears
    (backticked) in docs/OBSERVABILITY.md's runbook prose.

Checks whose inputs are absent from the tree (partial fixture trees)
are skipped, not failed.

Stdlib-only and self-contained (the bench_check file-path-load
contract, docs/STATICCHECK.md).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from npairloss_tpu.analysis.findings import Finding
from npairloss_tpu.analysis.tree import (
    SourceTree,
    const_str,
    module_level_constants,
    str_tuple,
)

PASS_NAME = "vocab"

RESILIENCE_DOC = "docs/RESILIENCE.md"
OBSERVABILITY_DOC = "docs/OBSERVABILITY.md"
WATCHDOGS_PY = "npairloss_tpu/obs/live/watchdogs.py"
CLI_PY = "npairloss_tpu/cli.py"

# (module holding a literal choices tuple, its name) pinned equal to
# (module holding the registry dict literal, its name).
CHOICE_PINS: List[Tuple[Tuple[str, str], Tuple[str, str]]] = [
    (("npairloss_tpu/cli.py", "_PRECISION_CHOICES"),
     ("npairloss_tpu/models/precision.py", "_POLICIES")),
    (("npairloss_tpu/cli.py", "_PROBE_IMPL_CHOICES"),
     ("npairloss_tpu/ops/pallas_ivf.py", "PROBE_IMPLS")),
    # The tenant manifest validator is jax-free (the bench_check
    # file-path-load contract), so its choice tuples restate the
    # registries they admit specs into — pinned here so a new probe
    # impl or index kind cannot land without the manifest accepting it.
    (("npairloss_tpu/serve/tenants.py", "_PROBE_IMPL_CHOICES"),
     ("npairloss_tpu/ops/pallas_ivf.py", "PROBE_IMPLS")),
    (("npairloss_tpu/serve/tenants.py", "_INDEX_KIND_CHOICES"),
     ("npairloss_tpu/serve/tenants.py", "INDEX_KINDS")),
]

# Entry-point spellings in documented command lines -> which argparse
# vocabulary governs their flags.
_ENTRYPOINTS: List[Tuple[re.Pattern, str]] = [
    (re.compile(r"python(?:3)?\s+-m\s+npairloss_tpu\s+(\S+)"), CLI_PY),
    (re.compile(r"(?:python(?:3)?\s+)?(?:scripts/)?bench_check\.py"),
     "scripts/bench_check.py"),
    (re.compile(r"(?:python(?:3)?\s+)?(?:\./)?bench\.py"), "bench.py"),
]

_BACKTICK_ROW_RE = re.compile(r"^\|\s*`([^`]+)`")
_FLAG_RE = re.compile(r"^--[A-Za-z][A-Za-z_0-9-]*")


def _failpoint_fires(tree: SourceTree) -> Dict[str, Tuple[str, int]]:
    """{name -> (path, line)} for every ``failpoints.fire``/
    ``failpoints.should_fire`` literal in the package."""
    out: Dict[str, Tuple[str, int]] = {}
    for rel in tree.py_files(subdirs=("npairloss_tpu",)):
        mod = tree.parse(rel)
        if mod is None:
            continue
        for node in ast.walk(mod):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr in ("fire", "should_fire")
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "failpoints"):
                continue
            lit = const_str(node.args[0])
            if lit:
                out.setdefault(lit, (rel, node.lineno))
    return out


def _doc_table_names(text: str, header_word: str) -> Optional[Set[str]]:
    """First-column backticked names of the markdown table whose header
    row contains ``header_word``; None when no such table exists."""
    lines = text.splitlines()
    names: Set[str] = set()
    found = False
    i = 0
    while i < len(lines):
        line = lines[i]
        if line.lstrip().startswith("|") and header_word in line.lower() \
                and i + 1 < len(lines) \
                and set(lines[i + 1].replace("|", "").strip()) <= set("-: "):
            found = True
            i += 2
            while i < len(lines) and lines[i].lstrip().startswith("|"):
                m = _BACKTICK_ROW_RE.match(lines[i].lstrip())
                if m:
                    names.add(m.group(1).strip())
                i += 1
            continue
        i += 1
    return names if found else None


def _argparse_vocab(tree: SourceTree, rel: str
                    ) -> Tuple[Set[str], Set[str]]:
    """(option strings, subcommand names) defined in ``rel`` — every
    ``add_argument('--x', ...)`` and ``add_parser('name', ...)``."""
    flags: Set[str] = set()
    subs: Set[str] = set()
    mod = tree.parse(rel)
    if mod is None:
        return flags, subs
    for node in ast.walk(mod):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else None
        if name == "add_argument":
            for arg in node.args:
                s = const_str(arg)
                if s and s.startswith("-"):
                    flags.add(s)
            flags.update(("-h", "--help"))  # argparse adds these itself
        elif name == "add_parser" and node.args:
            s = const_str(node.args[0])
            if s:
                subs.add(s)
    return flags, subs


def _doc_command_lines(text: str) -> List[Tuple[int, str]]:
    """(first line number, joined command) for each fenced-code line
    mentioning one of our entry points; backslash continuations are
    joined."""
    out: List[Tuple[int, str]] = []
    lines = text.splitlines()
    in_fence = False
    i = 0
    while i < len(lines):
        stripped = lines[i].strip()
        if stripped.startswith("```"):
            in_fence = not in_fence
            i += 1
            continue
        if in_fence and ("npairloss_tpu" in stripped
                         or "bench_check.py" in stripped
                         or "bench.py" in stripped):
            start = i + 1
            cmd = stripped
            while cmd.endswith("\\") and i + 1 < len(lines):
                i += 1
                cmd = cmd[:-1] + " " + lines[i].strip()
            out.append((start, cmd))
        i += 1
    return out


def _flags_of(cmd: str) -> List[str]:
    out = []
    for tok in cmd.split():
        m = _FLAG_RE.match(tok)
        if m:
            out.append(m.group(0))
    return out


def run(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []

    # -- failpoints vs the RESILIENCE.md table --
    fires = _failpoint_fires(tree)
    res_text = tree.text(RESILIENCE_DOC)
    documented = _doc_table_names(res_text, "failpoint") \
        if res_text is not None else None
    if fires and documented is not None:
        for name, (rel, line) in sorted(fires.items()):
            if name not in documented:
                findings.append(Finding(
                    PASS_NAME, rel, line, f"failpoint-{name}",
                    f"failpoint {name!r} is fired here but missing "
                    f"from the {RESILIENCE_DOC} failpoint table — an "
                    "undocumented fault injection nobody can drive "
                    "from the runbook"))
        for name in sorted(documented - set(fires)):
            findings.append(Finding(
                PASS_NAME, RESILIENCE_DOC, 0, f"failpoint-{name}",
                f"failpoint {name!r} is documented in the "
                f"{RESILIENCE_DOC} table but never fired anywhere in "
                "the package — stale row or dead injection point"))

    # -- documented command lines use real flags/subcommands --
    vocab_cache: Dict[str, Tuple[Set[str], Set[str]]] = {}
    for doc in tree.md_files():
        text = tree.text(doc)
        if text is None:
            continue
        for line_no, cmd in _doc_command_lines(text):
            for pat, vocab_rel in _ENTRYPOINTS:
                m = pat.search(cmd)
                if not m:
                    continue
                if not tree.exists(vocab_rel):
                    break
                if vocab_rel not in vocab_cache:
                    vocab_cache[vocab_rel] = _argparse_vocab(
                        tree, vocab_rel)
                flags, subs = vocab_cache[vocab_rel]
                if m.groups():
                    sub = m.group(1)
                    if subs and not sub.startswith("-") \
                            and sub not in subs:
                        findings.append(Finding(
                            PASS_NAME, doc, line_no, f"subcommand-{sub}",
                            f"documented command uses subcommand "
                            f"{sub!r} which {vocab_rel} does not "
                            f"define (known: {sorted(subs)})"))
                        break
                    if sub == "bench":
                        # `... bench` forwards its args to bench.py
                        # verbatim; check against THAT vocabulary.
                        if not tree.exists("bench.py"):
                            break
                        if "bench.py" not in vocab_cache:
                            vocab_cache["bench.py"] = _argparse_vocab(
                                tree, "bench.py")
                        flags, _ = vocab_cache["bench.py"]
                        vocab_rel = "bench.py"
                tail = cmd[m.end():]
                for flag in _flags_of(tail):
                    if flag not in flags:
                        findings.append(Finding(
                            PASS_NAME, doc, line_no, f"flag-{flag}",
                            f"documented command passes {flag} which "
                            f"{vocab_rel} does not define — runbook "
                            "drifted from argparse"))
                break

    # -- literal choice pins --
    for (rel_a, name_a), (rel_b, name_b) in CHOICE_PINS:
        if not (tree.exists(rel_a) and tree.exists(rel_b)):
            continue
        mod_a, mod_b = tree.parse(rel_a), tree.parse(rel_b)
        if mod_a is None or mod_b is None:
            continue
        val_a = module_level_constants(mod_a).get(name_a)
        choices = str_tuple(val_a) if val_a is not None else None
        val_b = module_level_constants(mod_b).get(name_b)
        registry: Optional[Set[str]] = None
        if isinstance(val_b, ast.Dict):
            keys = [const_str(k) for k in val_b.keys if k is not None]
            if all(k is not None for k in keys):
                registry = set(keys)
        if choices is None or registry is None:
            findings.append(Finding(
                PASS_NAME, rel_a, 0, f"pin-{name_a}",
                f"choice pin {name_a} ({rel_a}) vs {name_b} ({rel_b}) "
                "cannot be resolved to literals"))
        elif set(choices) != registry:
            findings.append(Finding(
                PASS_NAME, rel_a, val_a.lineno, f"pin-{name_a}",
                f"{name_a} {sorted(choices)} != {name_b} registry "
                f"keys {sorted(registry)} — the jax-free argparse "
                "vocabulary drifted from the registry"))

    # -- watchdog preset names documented --
    wd_mod = tree.parse(WATCHDOGS_PY) if tree.exists(WATCHDOGS_PY) \
        else None
    obs_text = tree.text(OBSERVABILITY_DOC)
    if wd_mod is not None and obs_text is not None:
        names: List[Tuple[str, int]] = []
        for node in ast.walk(wd_mod):
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == "name":
                        s = const_str(kw.value)
                        if s:
                            names.append((s, node.lineno))
        for name, line in sorted(set(names)):
            if f"`{name}`" not in obs_text:
                findings.append(Finding(
                    PASS_NAME, WATCHDOGS_PY, line, f"watchdog-{name}",
                    f"watchdog preset {name!r} is not mentioned "
                    f"(backticked) anywhere in {OBSERVABILITY_DOC} — "
                    "the runbook cannot explain an alert it never "
                    "names"))
    return findings
