"""Source-tree access shared by every staticcheck pass.

One parse per file per run: ``SourceTree`` caches AST parses, raw
text, and per-line comment maps (tokenize-based, so a ``#`` inside a
string never reads as a comment).  The tree is rooted anywhere — the
real repo, or a seeded fixture tree under ``tests/fixtures/staticcheck``
— and passes degrade gracefully when a root is partial (a fixture tree
carries only the files its violation needs).

Stdlib-only and self-contained (the bench_check file-path-load
contract, docs/STATICCHECK.md).
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from typing import Dict, List, Optional, Sequence, Tuple

# Directory names never descended into.  "fixtures" keeps the seeded
# violation trees under tests/fixtures/staticcheck from failing the
# real repo's own gate (each fixture is scanned as its OWN root).
SKIP_DIRS = {"__pycache__", ".git", "fixtures", "node_modules", ".claude"}

# Where library code lives relative to the root: the package and the
# CI/bench scripts.  Tests are scanned only by the marker pass (its
# own root list).
CODE_DIRS = ("npairloss_tpu", "scripts")


class SourceTree:
    """A rooted view of the files the passes read."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._ast: Dict[str, Optional[ast.Module]] = {}
        self._text: Dict[str, Optional[str]] = {}
        self._comments: Dict[str, Dict[int, str]] = {}
        self.parse_errors: List[Tuple[str, str]] = []
        # Files read since the last ``begin_pass()`` — cache hits
        # included, so a pass's files_scanned reports what it actually
        # LOOKED AT, not what it happened to parse first.
        self.touched: set = set()

    def begin_pass(self) -> None:
        self.touched = set()

    # -- discovery ---------------------------------------------------------

    def _walk(self, subdir: str, suffix: str) -> List[str]:
        base = os.path.join(self.root, subdir)
        out: List[str] = []
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(
                d for d in dirnames if d not in SKIP_DIRS)
            for name in sorted(filenames):
                if name.endswith(suffix):
                    rel = os.path.relpath(
                        os.path.join(dirpath, name), self.root)
                    out.append(rel.replace(os.sep, "/"))
        return out

    def py_files(self, subdirs: Sequence[str] = CODE_DIRS) -> List[str]:
        """Root-relative .py paths under ``subdirs``, sorted."""
        out: List[str] = []
        for sub in subdirs:
            out.extend(self._walk(sub, ".py"))
        return out

    def md_files(self, subdirs: Sequence[str] = ("docs", "")) -> List[str]:
        """Root-relative .md paths: docs/ recursively plus the root's
        own *.md (README.md and friends); "" means the root itself,
        non-recursive."""
        out: List[str] = []
        for sub in subdirs:
            if sub:
                out.extend(self._walk(sub, ".md"))
            else:
                try:
                    names = sorted(os.listdir(self.root))
                except OSError:
                    continue
                out.extend(n for n in names if n.endswith(".md")
                           and os.path.isfile(self.abspath(n)))
        return out

    # -- access ------------------------------------------------------------

    def abspath(self, rel: str) -> str:
        return os.path.join(self.root, rel.replace("/", os.sep))

    def exists(self, rel: str) -> bool:
        return os.path.isfile(self.abspath(rel))

    def text(self, rel: str) -> Optional[str]:
        self.touched.add(rel)
        if rel not in self._text:
            try:
                with open(self.abspath(rel), encoding="utf-8") as f:
                    self._text[rel] = f.read()
            except (OSError, UnicodeDecodeError):
                self._text[rel] = None
        return self._text[rel]

    def parse(self, rel: str) -> Optional[ast.Module]:
        """The file's AST, or None (recorded in ``parse_errors``) when
        it does not parse — a syntax error is reported once by the
        runner, not once per pass."""
        self.touched.add(rel)
        if rel not in self._ast:
            text = self.text(rel)
            if text is None:
                self._ast[rel] = None
                self.parse_errors.append((rel, "unreadable"))
            else:
                try:
                    self._ast[rel] = ast.parse(text, filename=rel)
                except SyntaxError as e:
                    self._ast[rel] = None
                    self.parse_errors.append((rel, f"syntax error: {e}"))
        return self._ast[rel]

    def comments(self, rel: str) -> Dict[int, str]:
        """{line -> comment text (without '#')} via tokenize; empty on
        unreadable/untokenizable files."""
        self.touched.add(rel)
        if rel not in self._comments:
            out: Dict[int, str] = {}
            text = self.text(rel)
            if text is not None:
                try:
                    for tok in tokenize.generate_tokens(
                            io.StringIO(text).readline):
                        if tok.type == tokenize.COMMENT:
                            out[tok.start[0]] = tok.string.lstrip("#").strip()
                except (tokenize.TokenError, IndentationError,
                        SyntaxError):
                    pass
            self._comments[rel] = out
        return self._comments[rel]


# -- small AST helpers shared by passes ---------------------------------------


def const_str(node: ast.AST) -> Optional[str]:
    """The literal string of a Constant node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    """A tuple/list literal of string constants, else None."""
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            s = const_str(elt)
            if s is None:
                return None
            out.append(s)
        return tuple(out)
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def module_level_constants(tree: ast.Module) -> Dict[str, ast.AST]:
    """{NAME -> value node} for simple module-level ``NAME = <expr>``
    assignments (including inside top-level try/if bodies)."""
    out: Dict[str, ast.AST] = {}

    def visit(stmts):
        for stmt in stmts:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                out[stmt.targets[0].id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                    and isinstance(stmt.target, ast.Name):
                out[stmt.target.id] = stmt.value
            elif isinstance(stmt, ast.If):
                visit(stmt.body)
                visit(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                visit(stmt.body)
                for h in stmt.handlers:
                    visit(h.body)
                visit(stmt.orelse)
                visit(stmt.finalbody)

    visit(tree.body)
    return out
