"""Pass ``contracts`` — writer/validator drift on the versioned schemas.

Every artifact the gates trust is a versioned contract
(``npairloss-*-v1``) with exactly one validator module; the emitter
key sets have literal "twins" pinned across jax-free module pairs
(``obs.sinks.FLEET_KEYS`` restates ``obs.fleet.stamp.STAMP_KEYS``
because the jax-free loader must not drag the package in).  Runtime
tests pin some of these; this pass proves ALL of them at lint time:

  * every module-level constant holding a ``npairloss-*-v<N>`` string
    is defined in exactly one module, and that module ships a
    ``validate_*`` function (no orphan writers, no orphan validators);
  * no other module restates the version literal in code (dict
    writes / comparisons) — import the constant or stay out;
  * declared KEY-TWIN literal pairs are element-for-element equal;
  * declared WRITER-PIN dict literals (e.g. ``FleetStamp.to_dict``)
    emit exactly the keys their ``*_KEYS`` constant promises.

Stdlib-only and self-contained (the bench_check file-path-load
contract, docs/STATICCHECK.md).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from npairloss_tpu.analysis.findings import Finding
from npairloss_tpu.analysis.tree import (
    SourceTree,
    const_str,
    module_level_constants,
    str_tuple,
)

PASS_NAME = "contracts"

SCHEMA_RE = re.compile(r"^npairloss-[a-z0-9][a-z0-9-]*-v\d+$")

# Literal tuples that must stay element-for-element identical across
# modules (the jax-free restatement contract).  Pairs where either
# side is absent from the tree are skipped (partial fixture trees).
KEY_TWINS: List[Tuple[Tuple[str, str], Tuple[str, str]]] = [
    (("npairloss_tpu/obs/sinks.py", "FLEET_KEYS"),
     ("npairloss_tpu/obs/fleet/stamp.py", "STAMP_KEYS")),
]

# (module, dotted function/method, keys-constant in the same module):
# the function's returned dict literal must emit exactly those keys.
WRITER_PINS: List[Tuple[str, str, str]] = [
    ("npairloss_tpu/obs/fleet/stamp.py", "FleetStamp.to_dict",
     "STAMP_KEYS"),
    # The suite holds itself to its own contract.
    ("npairloss_tpu/analysis/report.py", "build_report", "REPORT_KEYS"),
]


def _find_func(mod: ast.Module, dotted: str) -> Optional[ast.FunctionDef]:
    parts = dotted.split(".")
    body = mod.body
    node: Optional[ast.AST] = None
    for i, part in enumerate(parts):
        node = None
        for stmt in body:
            if isinstance(stmt, (ast.ClassDef, ast.FunctionDef)) and \
                    stmt.name == part:
                node = stmt
                break
        if node is None:
            return None
        body = getattr(node, "body", [])
    return node if isinstance(node, ast.FunctionDef) else None


def _returned_dict_keys(fn: ast.FunctionDef) -> Optional[Tuple[str, ...]]:
    """Constant keys of the function's ``return {...}`` dict literal
    (first such return); None when there is none or keys are dynamic."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
            keys = []
            for k in node.value.keys:
                s = const_str(k) if k is not None else None
                if s is None:
                    return None
                keys.append(s)
            return tuple(keys)
    return None


def _schema_constants(tree: SourceTree, rel: str) -> Dict[str, Tuple[str, int]]:
    """{version-string -> (const name, line)} for module-level
    constants of ``rel`` holding a versioned schema literal."""
    mod = tree.parse(rel)
    if mod is None:
        return {}
    out: Dict[str, Tuple[str, int]] = {}
    for name, value in module_level_constants(mod).items():
        s = const_str(value)
        if s and SCHEMA_RE.match(s):
            out[s] = (name, value.lineno)
    return out


def _restated_literals(mod: ast.Module) -> List[Tuple[str, int]]:
    """Versioned literals appearing in CODE context — dict writes and
    comparisons — where the constant should have been used instead.
    Docstrings and help text never match these contexts."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(mod):
        exprs: List[ast.AST] = []
        if isinstance(node, ast.Dict):
            exprs.extend(k for k in node.keys if k is not None)
            exprs.extend(node.values)
        elif isinstance(node, ast.Compare):
            exprs.append(node.left)
            exprs.extend(node.comparators)
        for e in exprs:
            s = const_str(e)
            if s and SCHEMA_RE.match(s):
                out.append((s, e.lineno))
    return out


def run(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []
    files = tree.py_files()

    # -- schema registry: one defining module per version, each with a
    # validator --
    defined: Dict[str, List[Tuple[str, str, int]]] = {}
    for rel in files:
        for schema, (name, line) in _schema_constants(tree, rel).items():
            defined.setdefault(schema, []).append((rel, name, line))
    for schema, sites in sorted(defined.items()):
        if len(sites) > 1:
            where = ", ".join(f"{r}:{ln} ({n})" for r, n, ln in sites)
            for rel, name, line in sites:
                findings.append(Finding(
                    PASS_NAME, rel, line, schema,
                    f"version string {schema!r} is defined in "
                    f"{len(sites)} modules ({where}) — one contract, "
                    "one defining module"))
            continue
        rel, name, line = sites[0]
        mod = tree.parse(rel)
        has_validator = mod is not None and any(
            isinstance(stmt, ast.FunctionDef)
            and stmt.name.startswith("validate_")
            for stmt in mod.body)
        if not has_validator:
            findings.append(Finding(
                PASS_NAME, rel, line, schema,
                f"{name} = {schema!r} has no module-level "
                "validate_* function in its defining module — a "
                "versioned contract without a validator is an orphan "
                "writer (the gates have nothing to hold it to)"))

    # -- no restated literals outside the defining module --
    for rel in files:
        mod = tree.parse(rel)
        if mod is None:
            continue
        for schema, line in _restated_literals(mod):
            sites = defined.get(schema)
            if sites and sites[0][0] != rel:
                findings.append(Finding(
                    PASS_NAME, rel, line, f"restated-{schema}",
                    f"{schema!r} restated as a raw literal outside its "
                    f"defining module ({sites[0][0]}) — import the "
                    "constant so a version bump cannot fork the "
                    "contract"))

    # -- key twins --
    for (rel_a, name_a), (rel_b, name_b) in KEY_TWINS:
        if not (tree.exists(rel_a) and tree.exists(rel_b)):
            continue
        mod_a, mod_b = tree.parse(rel_a), tree.parse(rel_b)
        if mod_a is None or mod_b is None:
            continue
        val_a = module_level_constants(mod_a).get(name_a)
        val_b = module_level_constants(mod_b).get(name_b)
        tup_a = str_tuple(val_a) if val_a is not None else None
        tup_b = str_tuple(val_b) if val_b is not None else None
        for rel, name, tup in ((rel_a, name_a, tup_a),
                               (rel_b, name_b, tup_b)):
            if tup is None:
                findings.append(Finding(
                    PASS_NAME, rel, 0, f"twin-{name}",
                    f"{name} in {rel} is missing or not a literal "
                    "string tuple — the key-twin pin cannot be "
                    "proven"))
        if tup_a is not None and tup_b is not None and tup_a != tup_b:
            findings.append(Finding(
                PASS_NAME, rel_a, val_a.lineno, f"twin-{name_a}",
                f"{name_a} {tup_a} != {rel_b}:{name_b} {tup_b} — the "
                "jax-free restatement drifted from its twin"))

    # -- writer pins --
    for rel, dotted, keys_name in WRITER_PINS:
        if not tree.exists(rel):
            continue
        mod = tree.parse(rel)
        if mod is None:
            continue
        fn = _find_func(mod, dotted)
        keys_val = module_level_constants(mod).get(keys_name)
        keys = str_tuple(keys_val) if keys_val is not None else None
        if fn is None or keys is None:
            findings.append(Finding(
                PASS_NAME, rel, 0, f"pin-{dotted}",
                f"writer pin {dotted} <-> {keys_name} cannot be "
                "resolved (function or literal keys constant missing)"))
            continue
        emitted = _returned_dict_keys(fn)
        if emitted is None:
            findings.append(Finding(
                PASS_NAME, rel, fn.lineno, f"pin-{dotted}",
                f"{dotted} does not return a literal dict — the "
                f"writer pin against {keys_name} cannot be proven"))
        elif set(emitted) != set(keys):
            findings.append(Finding(
                PASS_NAME, rel, fn.lineno, f"pin-{dotted}",
                f"{dotted} emits keys {sorted(emitted)} but "
                f"{keys_name} promises {sorted(keys)} — writer and "
                "contract drifted"))
    return findings
