"""The staticcheck suite driver: passes -> findings -> report -> gate.

``run_suite(root)`` runs every pass over one tree, applies the
committed allowlist (``scripts/staticcheck_allow.json`` under the
root — finding *keys*, which are line-number-free, so tolerated
findings survive unrelated edits), optionally restricts findings to
files changed since a git ref (``--diff BASE``, the fast incremental
ci.sh hook), and emits the versioned ``npairloss-staticcheck-v1``
report through ``analysis.report``.

Exposed three ways, all the same code path:

  * ``python -m npairloss_tpu staticcheck`` (cli.py subcommand —
    jax-free end to end, runnable in a venv without jax);
  * ``scripts/bench_check.py --static [ROOT]`` (the CI gate;
    file-path-loads this chain, never imports the package);
  * ``npairloss_tpu.analysis.run_suite`` (tests).

Stdlib-only and self-contained (the contract the purity pass proves
about this very package).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from npairloss_tpu.analysis import (
    contracts,
    locks,
    markers,
    purity,
    scopes,
    vocab,
)
from npairloss_tpu.analysis.findings import Finding
from npairloss_tpu.analysis.report import (
    build_report,
    validate_staticcheck_report,
    write_report,
)
from npairloss_tpu.analysis.tree import SourceTree

ALLOWLIST_PATH = "scripts/staticcheck_allow.json"

# Execution order: cheap vocabulary/contract scans first, the graph
# walks last — irrelevant for correctness, pleasant for humans.
PASSES: List[Tuple[str, Callable[[SourceTree], List[Finding]]]] = [
    (purity.PASS_NAME, purity.run),
    (scopes.PASS_NAME, scopes.run),
    (locks.PASS_NAME, locks.run),
    (contracts.PASS_NAME, contracts.run),
    (vocab.PASS_NAME, vocab.run),
    (markers.PASS_NAME, markers.run),
]

PASS_NAMES = tuple(name for name, _ in PASSES)


def load_allowlist(path: str) -> List[str]:
    """The committed allowlist: ``{"allow": [{"key": ..., "why": ...}
    | "<key>", ...]}``; a missing file is an empty allowlist."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except FileNotFoundError:
        return []
    except (OSError, ValueError) as e:
        raise ValueError(f"allowlist {path} unreadable: {e}")
    entries = obj.get("allow", []) if isinstance(obj, dict) else None
    if entries is None or not isinstance(entries, list):
        raise ValueError(
            f"allowlist {path} must be an object with an 'allow' list")
    keys: List[str] = []
    for i, entry in enumerate(entries):
        if isinstance(entry, str):
            keys.append(entry)
        elif isinstance(entry, dict) and isinstance(entry.get("key"), str):
            keys.append(entry["key"])
        else:
            raise ValueError(
                f"allowlist {path} entry {i} must be a key string or "
                "an object with a 'key'")
    return keys


def changed_files(root: str, base: str) -> Optional[List[str]]:
    """Root-relative files changed since ``base`` (worktree vs ref,
    plus untracked); None when git cannot answer (not a repo, bad
    ref) — the caller degrades to a full run, loudly."""
    out: List[str] = []
    # --relative keeps diff paths cwd-relative like ls-files' already
    # are — without it, running on a SUBTREE root (a fixture dir)
    # yields repo-root-relative diff paths that never match the
    # tree-relative finding paths, silently dropping findings.
    for args in (["git", "diff", "--name-only", "--relative", base],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(
                args, cwd=root, capture_output=True, text=True,
                timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        out.extend(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return sorted(set(out))


def run_suite(
    root: str,
    passes: Optional[Sequence[str]] = None,
    diff_base: Optional[str] = None,
    allowlist_path: Optional[str] = None,
) -> Dict[str, Any]:
    """Run the suite; returns the ``npairloss-staticcheck-v1`` report
    (already validator-clean — asserted here, the suite holds itself
    to its own contract)."""
    tree = SourceTree(root)
    selected = set(passes) if passes else set(PASS_NAMES)
    unknown = selected - set(PASS_NAMES)
    if unknown:
        raise ValueError(f"unknown pass(es) {sorted(unknown)} "
                         f"(known: {list(PASS_NAMES)})")

    if allowlist_path is None:
        allowlist_path = os.path.join(tree.root, ALLOWLIST_PATH)
    allow = set(load_allowlist(allowlist_path))

    changed: Optional[set] = None
    if diff_base is not None:
        files = changed_files(tree.root, diff_base)
        if files is None:
            raise ValueError(
                f"--diff {diff_base}: git could not enumerate changes "
                f"under {tree.root} — run without --diff")
        changed = set(files)

    pass_rows: List[Dict[str, Any]] = []
    findings: List[Finding] = []
    for name, fn in PASSES:
        if name not in selected:
            continue
        tree.begin_pass()
        note = ""
        if name == markers.PASS_NAME and \
                not tree.exists(markers.HISTORY_PATH):
            pass_rows.append({
                "name": name, "files_scanned": 0, "findings": 0,
                "skipped": True,
                "note": f"no {markers.HISTORY_PATH} in this tree "
                        "(regenerate with --update-timings)"})
            continue
        got = fn(tree)
        if changed is not None:
            got = [f for f in got if f.path in changed]
            note = f"restricted to {len(changed)} changed file(s)"
        findings.extend(got)
        pass_rows.append({
            "name": name,
            "files_scanned": len(tree.touched),
            "findings": len(got),
            "skipped": False,
            "note": note,
        })

    anchor = next((row for row in pass_rows if not row["skipped"]), None)
    if anchor is not None:
        for rel, err in tree.parse_errors:
            if changed is not None and rel not in changed:
                continue  # the --diff contract: unrelated files stay out
            findings.append(Finding(
                anchor["name"], rel, 0, "parse-error",
                f"file does not parse ({err}) — no pass can vouch "
                "for it"))
            anchor["findings"] += 1

    hard = [f for f in findings if f.key not in allow]
    allowed = [f for f in findings if f.key in allow]
    report = build_report(
        tree.root,
        pass_rows,
        [f.to_dict() for f in hard],
        [f.to_dict() for f in allowed],
    )
    err = validate_staticcheck_report(report)
    if err is not None:  # the suite's own bug, never the tree's
        raise AssertionError(f"staticcheck emitted an invalid report: "
                             f"{err}")
    return report


def render(report: Dict[str, Any], stream=None) -> None:
    stream = stream or sys.stdout
    for p in report["passes"]:
        state = "skipped" if p["skipped"] else (
            f"{p['findings']} finding(s)")
        note = f" — {p['note']}" if p["note"] else ""
        print(f"[staticcheck] {p['name']}: {state}{note}", file=stream)
    for rec in report["findings"]:
        loc = f"{rec['path']}:{rec['line']}" if rec["line"] \
            else rec["path"]
        print(f"FINDING [{rec['pass']}] {loc}: {rec['message']}",
              file=stream)
    n_allow = report["summary"]["allowlisted"]
    if n_allow:
        print(f"[staticcheck] {n_allow} allowlisted finding(s) "
              "tolerated", file=stream)


def update_timings(root: str, log_path: str,
                   threshold_s: float) -> str:
    """Regenerate ``tests/timing_history.json`` from a pytest
    ``--durations=0`` log; returns the path written."""
    with open(log_path) as f:
        durations = markers.parse_durations_log(f.read())
    if not durations:
        raise ValueError(
            f"{log_path} holds no pytest duration lines — run tier-1 "
            "with --durations=0 and pass that log")
    out = os.path.join(root, markers.HISTORY_PATH)
    payload = {
        "threshold_s": threshold_s,
        "source": os.path.basename(log_path),
        "durations": {k: round(v, 3)
                      for k, v in sorted(durations.items())},
    }
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, out)
    return out


def run_from_args(args, default_root: str) -> int:
    """The one driver body behind both entry points (``python -m
    npairloss_tpu staticcheck`` and ``python -m
    npairloss_tpu.analysis.runner``): expects the argparse namespace
    shape both parsers produce (root / passes / diff / allowlist /
    out / update_timings / threshold_s — the option sets are pinned
    equal by tests/test_staticcheck.py)."""
    root = args.root or default_root

    if args.update_timings:
        try:
            out = update_timings(root, args.update_timings,
                                 args.threshold_s)
        except (OSError, ValueError) as e:
            print(f"staticcheck: {e}", file=sys.stderr)
            return 2
        print(f"[staticcheck] wrote {out}")
        return 0

    try:
        report = run_suite(root, passes=args.passes,
                           diff_base=args.diff,
                           allowlist_path=args.allowlist)
    except ValueError as e:
        print(f"staticcheck: {e}", file=sys.stderr)
        return 2
    render(report)
    if args.out and args.out != "-":
        write_report(report, args.out)
        print(f"[staticcheck] report: {args.out}")
    n = report["summary"]["findings"]
    if n:
        print(f"staticcheck: {n} finding(s)")
        return 1
    print("staticcheck OK (no findings)")
    return 0


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="staticcheck",
        description="repo-wide invariant linter (docs/STATICCHECK.md)")
    ap.add_argument("root", nargs="?", default=None,
                    help="tree to scan (default: the repo this module "
                    "lives in)")
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=list(PASS_NAMES), metavar="NAME",
                    help="run only the named pass(es); repeatable "
                    f"(default: all of {list(PASS_NAMES)})")
    ap.add_argument("--diff", metavar="BASE",
                    help="restrict findings to files changed since the "
                    "git ref (the incremental ci hook)")
    ap.add_argument("--allowlist", metavar="PATH",
                    help=f"allowlist JSON (default: <root>/"
                    f"{ALLOWLIST_PATH})")
    ap.add_argument("--out", metavar="PATH",
                    default="staticcheck_report.json",
                    help="where the npairloss-staticcheck-v1 report "
                    "lands (default: ./staticcheck_report.json; '-' "
                    "disables the artifact)")
    ap.add_argument("--update-timings", metavar="PYTEST_LOG",
                    help="regenerate tests/timing_history.json from a "
                    "pytest --durations=0 log, then exit")
    ap.add_argument("--threshold-s", type=float,
                    default=markers.DEFAULT_THRESHOLD_S,
                    help="slow-marker threshold recorded by "
                    "--update-timings (default %(default)s)")
    return ap


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    return run_from_args(args, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))


if __name__ == "__main__":
    sys.exit(main())
