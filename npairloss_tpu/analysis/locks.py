"""Pass ``locks`` — ``# guarded-by:`` discipline on shared mutable state.

The threaded registries (MetricRegistry fed by request threads,
SLOEvaluator ticked by the observatory while /healthz scrapes read,
RemediationEngine, RetrievalServer's hot-swap state) rely on every
mutation happening under one lock — a discipline previously enforced
only by review and by the races that slipped past it (the PR-10
read-only /healthz evaluate fix was exactly such a slip).

Convention (docs/STATICCHECK.md §Annotations):

  * declare: ``self.attr = ...  # guarded-by: _lock`` — usually in
    ``__init__``; the lock is named by its own attribute name;
  * the checker flags any mutation of a declared attribute (assign,
    augassign, del, subscript-store, or a mutating method call like
    ``.append``/``.update``) in any method that is not lexically
    inside ``with self._lock:``;
  * ``__init__``/``__new__``/``__post_init__`` are exempt
    (construction happens-before sharing);
  * a method whose ``def`` line carries ``# holds-lock: _lock``
    declares its callers hold the lock (checked as if enclosed);
  * one mutation line may carry ``# unguarded-ok: <reason>`` for a
    documented deliberate exception;
  * a nested function body does NOT inherit the enclosing ``with`` —
    it runs when called, not where defined (callbacks escape locks).

Stdlib-only and self-contained (the bench_check file-path-load
contract, docs/STATICCHECK.md).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from npairloss_tpu.analysis.findings import Finding
from npairloss_tpu.analysis.tree import SourceTree

PASS_NAME = "locks"

GUARDED_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z_0-9]*)")
HOLDS_RE = re.compile(r"holds-lock:\s*([A-Za-z_][A-Za-z_0-9]*)")
UNGUARDED_OK = "unguarded-ok"

EXEMPT_METHODS = {"__init__", "__new__", "__post_init__", "__del__"}

MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "clear", "remove", "discard", "setdefault", "appendleft",
    "extendleft", "sort", "reverse",
})


def _self_attr(node: ast.AST) -> str:
    """'attr' for a ``self.attr`` Attribute node, else ''."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return ""


def _self_attr_base(node: ast.AST) -> str:
    """The self-attribute at the base of a Subscript/Attribute chain:
    ``self._last[p][k]`` -> '_last' (``self.x.y`` deliberately not —
    the owned object's own attribute is its own class's discipline)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return _self_attr(node)


def _mutated_attrs(stmt: ast.AST) -> List[str]:
    """Declared-attr mutation targets of one statement node.  Mutating
    METHOD calls (``self._d.pop(...)``) are handled separately in the
    walker — they mutate in any expression context, not only as bare
    statements."""
    out: List[str] = []

    def target_attrs(t: ast.AST):
        if isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                target_attrs(elt)
            return
        a = _self_attr(t) or _self_attr_base(t)
        if a:
            out.append(a)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            target_attrs(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        target_attrs(stmt.target)
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            target_attrs(t)
    return out


def _mutating_call_attr(node: ast.AST) -> str:
    """The self-attribute a Call node mutates (``self._d.pop(k)`` in
    ANY expression context — ``x = self._d.pop(k)`` counts exactly
    like the bare-statement form), else ''."""
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATING_METHODS:
            return _self_attr_base(fn.value)
    return ""


def _with_locks(node: ast.With) -> Set[str]:
    """Lock attribute names this ``with`` acquires (``self.X`` items)."""
    out: Set[str] = set()
    for item in node.items:
        a = _self_attr(item.context_expr)
        if a:
            out.add(a)
    return out


def guarded_attrs(cls: ast.ClassDef, comments: Dict[int, str]
                  ) -> Dict[str, str]:
    """{attr -> lock} declared via ``# guarded-by:`` in this class —
    the registration half, exposed so tests can pin that a real
    annotation actually arms the checker."""
    guarded: Dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            # The annotation may trail any line the (possibly
            # backslash-continued) assignment spans.
            note = "".join(
                comments.get(ln, "")
                for ln in range(node.lineno,
                                (node.end_lineno or node.lineno) + 1))
            m = GUARDED_RE.search(note)
            if m:
                for attr in _mutated_attrs(node):
                    guarded[attr] = m.group(1)
    return guarded


def _check_class(rel: str, cls: ast.ClassDef, comments: Dict[int, str],
                 findings: List[Finding]) -> None:
    guarded = guarded_attrs(cls, comments)
    if not guarded:
        return
    assigned_attrs: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            assigned_attrs.update(_mutated_attrs(node))
    for attr, lock in sorted(guarded.items()):
        if lock not in assigned_attrs:
            findings.append(Finding(
                PASS_NAME, rel, cls.lineno, f"{cls.name}.{attr}",
                f"{cls.name}.{attr} is '# guarded-by: {lock}' but no "
                f"'self.{lock}' is ever assigned in the class — the "
                "named lock does not exist"))

    def visit(node: ast.AST, held: Set[str], method: str) -> None:
        if isinstance(node, ast.With):
            inner = held | _with_locks(node)
            for item in node.items:
                visit(item, held, method)
            for child in node.body:
                visit(child, inner, method)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # A nested function runs when CALLED — it escapes the
            # enclosing with unless its def line declares holds-lock.
            inner: Set[str] = set()
            m = HOLDS_RE.search(comments.get(node.lineno, ""))
            if m:
                inner.add(m.group(1))
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                visit(child, inner, method)
            return
        mutated = _mutated_attrs(node)
        call_attr = _mutating_call_attr(node)
        if call_attr:
            mutated.append(call_attr)
        for attr in mutated:
            lock = guarded.get(attr)
            if lock and lock not in held:
                # The annotation may trail any line the mutation
                # spans, or sit directly above it (long lines).
                note = comments.get(node.lineno - 1, "") + "".join(
                    comments.get(ln, "")
                    for ln in range(
                        node.lineno,
                        (getattr(node, "end_lineno", None)
                         or node.lineno) + 1))
                if UNGUARDED_OK not in note:
                    findings.append(Finding(
                        PASS_NAME, rel, node.lineno,
                        f"{cls.name}.{method}.{attr}",
                        f"{cls.name}.{method} mutates self.{attr} "
                        f"(guarded-by: {lock}) outside 'with "
                        f"self.{lock}:' — annotate the def with "
                        f"'# holds-lock: {lock}' if callers hold it, "
                        f"or '# {UNGUARDED_OK}: <reason>' on the line"))
        for child in ast.iter_child_nodes(node):
            visit(child, held, method)

    for stmt in cls.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if stmt.name in EXEMPT_METHODS:
            continue
        held: Set[str] = set()
        m = HOLDS_RE.search(comments.get(stmt.lineno, ""))
        if m:
            held.add(m.group(1))
        for child in stmt.body:
            visit(child, held, stmt.name)


def run(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []
    for rel in tree.py_files(subdirs=("npairloss_tpu",)):
        mod = tree.parse(rel)
        if mod is None:
            continue
        comments = tree.comments(rel)
        for node in ast.walk(mod):
            if isinstance(node, ast.ClassDef):
                _check_class(rel, node, comments, findings)
    return findings
