"""The finding model every staticcheck pass speaks.

A finding is one violated invariant at one place: ``(pass_name, path,
line, key, message)``.  The ``key`` is the stable identity used by the
allowlist — deliberately line-number-free (``pass:path:detail``) so an
unrelated edit above a tolerated finding does not un-suppress it.

Stdlib-only and self-contained: ``scripts/bench_check.py --static``
file-path-loads the whole analysis chain from a jax-free process, the
same contract as ``obs.live.alerts`` (docs/STATICCHECK.md).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation.

    ``path`` is root-relative with forward slashes; ``line`` is
    1-based (0 = whole-file / cross-file finding anchored at ``path``);
    ``detail`` names the symbol or vocabulary item, NOT the position.
    """

    pass_name: str
    path: str
    line: int
    detail: str
    message: str

    @property
    def key(self) -> str:
        return f"{self.pass_name}:{self.path}:{self.detail}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "pass": self.pass_name,
            "path": self.path,
            "line": self.line,
            "key": self.key,
            "message": self.message,
        }

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"[{self.pass_name}] {loc}: {self.message}"
