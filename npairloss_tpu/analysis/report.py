"""The versioned ``npairloss-staticcheck-v1`` contract: the lint report.

One JSON object per suite run, written through the same
validate-contract pattern as every other gate artifact
(``validate_staticcheck_report`` IS the contract; consumers rely on
exactly the keys it checks).  ``scripts/bench_check.py --static``
file-path-loads this module from a jax-free process, so it keeps zero
intra-package imports beyond the analysis chain (stdlib only).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

STATICCHECK_SCHEMA = "npairloss-staticcheck-v1"

# Keys every report carries (the writer pin in analysis/contracts.py
# holds build_report to this literal).
REPORT_KEYS = ("schema", "root", "passes", "findings", "allowlisted",
               "summary")
PASS_KEYS = ("name", "files_scanned", "findings", "skipped", "note")
FINDING_KEYS = ("pass", "path", "line", "key", "message")
SUMMARY_KEYS = ("passes", "files_scanned", "findings", "allowlisted")


def build_report(root: str, passes: Sequence[Dict[str, Any]],
                 findings: Sequence[Dict[str, Any]],
                 allowlisted: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    return {
        "schema": STATICCHECK_SCHEMA,
        "root": os.path.abspath(root),
        "passes": list(passes),
        "findings": list(findings),
        "allowlisted": list(allowlisted),
        "summary": {
            "passes": len(passes),
            "files_scanned": sum(p.get("files_scanned", 0)
                                 for p in passes),
            "findings": len(findings),
            "allowlisted": len(allowlisted),
        },
    }


def _check_finding(i: int, rec: Any, kind: str,
                   pass_names: Sequence[str]) -> Optional[str]:
    if not isinstance(rec, dict):
        return f"{kind}[{i}] is not an object"
    for key in FINDING_KEYS:
        if key not in rec:
            return f"{kind}[{i}] missing {key!r}"
    if rec["pass"] not in pass_names:
        return (f"{kind}[{i}]: pass {rec['pass']!r} not in the "
                f"report's pass list {sorted(pass_names)}")
    if not isinstance(rec["line"], int) or rec["line"] < 0:
        return f"{kind}[{i}]: line must be an integer >= 0"
    if not isinstance(rec["path"], str) or not rec["path"]:
        return f"{kind}[{i}]: path must be a non-empty string"
    expect = f"{rec['pass']}:{rec['path']}:"
    if not isinstance(rec["key"], str) or \
            not rec["key"].startswith(expect):
        return (f"{kind}[{i}]: key {rec.get('key')!r} does not follow "
                f"'<pass>:<path>:<detail>' ({expect}...)")
    if not isinstance(rec["message"], str) or not rec["message"]:
        return f"{kind}[{i}]: message must be a non-empty string"
    return None


def validate_staticcheck_report(report: Any) -> Optional[str]:
    """Schema check; returns an error string or None.

    The contract: the schema tag; a non-empty ``passes`` list whose
    entries carry name/files_scanned/findings/skipped/note with a
    per-pass findings count that equals the findings+allowlisted
    records claiming that pass; finding records keyed
    ``<pass>:<path>:<detail>``; and a summary whose counts restate
    the lists (a consumer may trust either).
    """
    if not isinstance(report, dict):
        return "report is not an object"
    if report.get("schema") != STATICCHECK_SCHEMA:
        return (f"schema must be {STATICCHECK_SCHEMA!r}, got "
                f"{report.get('schema')!r}")
    for key in REPORT_KEYS:
        if key not in report:
            return f"report missing {key!r}"
    if not isinstance(report["root"], str) or not report["root"]:
        return "root must be a non-empty string"
    passes = report["passes"]
    if not isinstance(passes, list) or not passes:
        return "passes must be a non-empty list (a suite that ran "\
            "nothing checked nothing)"
    names: List[str] = []
    for i, p in enumerate(passes):
        if not isinstance(p, dict):
            return f"passes[{i}] is not an object"
        for key in PASS_KEYS:
            if key not in p:
                return f"passes[{i}] missing {key!r}"
        if not isinstance(p["name"], str) or not p["name"]:
            return f"passes[{i}]: name must be a non-empty string"
        if p["name"] in names:
            return f"passes[{i}]: duplicate pass {p['name']!r}"
        names.append(p["name"])
        for key in ("files_scanned", "findings"):
            if not isinstance(p[key], int) or p[key] < 0:
                return f"passes[{i}]: {key} must be an integer >= 0"
        if not isinstance(p["skipped"], bool):
            return f"passes[{i}]: skipped must be a bool"
        if p["skipped"] and p["findings"]:
            return (f"passes[{i}]: a skipped pass cannot claim "
                    "findings")
    for kind in ("findings", "allowlisted"):
        recs = report[kind]
        if not isinstance(recs, list):
            return f"{kind} must be a list"
        for i, rec in enumerate(recs):
            err = _check_finding(i, rec, kind, names)
            if err:
                return err
    per_pass: Dict[str, int] = {n: 0 for n in names}
    for kind in ("findings", "allowlisted"):
        for rec in report[kind]:
            per_pass[rec["pass"]] += 1
    for p in passes:
        if p["findings"] != per_pass[p["name"]]:
            return (f"pass {p['name']!r} claims {p['findings']} "
                    f"finding(s) but the record lists hold "
                    f"{per_pass[p['name']]}")
    summary = report["summary"]
    if not isinstance(summary, dict):
        return "summary is not an object"
    for key in SUMMARY_KEYS:
        if key not in summary:
            return f"summary missing {key!r}"
    if summary["passes"] != len(passes):
        return (f"summary.passes {summary['passes']} != "
                f"{len(passes)} pass entries")
    if summary["findings"] != len(report["findings"]):
        return (f"summary.findings {summary['findings']} != "
                f"{len(report['findings'])} finding records")
    if summary["allowlisted"] != len(report["allowlisted"]):
        return (f"summary.allowlisted {summary['allowlisted']} != "
                f"{len(report['allowlisted'])} allowlisted records")
    return None


def write_report(report: Dict[str, Any], path: str) -> None:
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def load_report(path: str) -> Any:
    with open(path) as f:
        return json.load(f)
