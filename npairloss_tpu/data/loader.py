"""The MultibatchData pipeline: sample -> decode (host threads) -> augment
(device, jitted) -> prefetch queue.

The reference's data layer runs decode + augmentation on a CPU prefetch
thread per rank (SURVEY.md §3.5).  Here the host only decodes and
resizes; every augmentation op (warp, crop, mirror, mean) runs on the
accelerator as one jitted graph (``data.transforms``), and a background
thread keeps a bounded queue of ready batches so the training step never
waits on input.
"""

from __future__ import annotations

import queue
import threading
import weakref
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from npairloss_tpu.config.schema import DataLayerConfig, TransformerConfig
from npairloss_tpu.data.dataset import ArrayDataset, ListFileDataset
from npairloss_tpu.data.sampler import IdentityBalancedSampler
from npairloss_tpu.data.transforms import augment


def _identity_counts(cfg: DataLayerConfig) -> Tuple[int, int]:
    ids = cfg.identity_num_per_batch
    imgs = cfg.img_num_per_identity
    if not ids or not imgs:
        # Fall back to pairs (the minimum the mining contract allows).
        imgs = imgs or 2
        ids = ids or max(1, (cfg.batch_size or 2) // imgs)
    return ids, imgs


class MultibatchLoader:
    """Iterator of (images[float32 NHWC], labels[int32]) batches."""

    def __init__(
        self,
        dataset,
        cfg: DataLayerConfig,
        transformer: Optional[TransformerConfig] = None,
        train: bool = True,
        seed: int = 0,
        prefetch: int = 2,
    ):
        self.dataset = dataset
        self.cfg = cfg
        self.transformer = transformer
        self.train = train
        ids, imgs = _identity_counts(cfg)
        self.sampler = IdentityBalancedSampler(
            dataset.labels,
            ids,
            imgs,
            rand_identity=cfg.rand_identity,
            shuffle=cfg.shuffle,
            seed=seed,
        )
        self._key = jax.random.PRNGKey(seed)
        self._queue: queue.Queue = queue.Queue(maxsize=max(prefetch, 1))
        self._stop = threading.Event()
        # The worker holds only a weakref to the loader, so an abandoned
        # loader (no close()) is still garbage-collectable; __del__ then
        # stops the thread.
        self._thread = threading.Thread(
            target=_prefetch_worker,
            args=(weakref.ref(self), self._queue, self._stop),
            daemon=True,
        )
        self._thread.start()

    # -- host side: sample + decode (see _prefetch_worker) -----------------

    def _produce_one(self):
        idx = next(self.sampler)
        images = self.dataset.load_batch(idx).astype(np.float32)
        labels = self.dataset.labels[idx].astype(np.int32)
        return images, labels


    # -- device side: augmentation -----------------------------------------

    def _augment(self, images: np.ndarray):
        self._key, sub = jax.random.split(self._key)
        return augment(
            images,
            sub,
            tp=self.cfg.transform,
            transformer=self.transformer,
            train=self.train,
        )

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration("loader is closed")
        item = self._queue.get()
        if isinstance(item, BaseException):
            self._stop.set()
            raise RuntimeError("data prefetch worker failed") from item
        images, labels = item
        if (
            self.cfg.transform != type(self.cfg.transform)()
            or self.transformer is not None
        ):
            images = self._augment(images)
        return images, labels

    def close(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        # The worker only weakrefs the loader, so this runs even without
        # close(); stop the thread rather than leak it.
        try:
            self._stop.set()
        except AttributeError:
            pass


def _prefetch_worker(loader_ref, q: queue.Queue, stop: threading.Event):
    """Module-level worker holding only a weakref to the loader (plus its
    queue/stop-event, which don't reference back), so an abandoned loader
    is garbage-collectable even while the worker blocks on a full queue."""

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=1.0)
                return True
            except queue.Full:
                continue
        return False

    while not stop.is_set():
        loader = loader_ref()
        if loader is None:
            return
        try:
            item = loader._produce_one()
            fatal = False
        except BaseException as exc:  # surface in __next__, not silently
            item, fatal = exc, True
        del loader  # no strong ref while blocking on the queue
        if not put(item) or fatal:
            return


def multibatch_loader(
    cfg: DataLayerConfig,
    transformer: Optional[TransformerConfig] = None,
    train: Optional[bool] = None,
    seed: int = 0,
    prefetch: int = 2,
) -> MultibatchLoader:
    """Build the full pipeline from a parsed MultibatchData layer config."""
    dataset = ListFileDataset(
        cfg.root_folder, cfg.source, cfg.new_height, cfg.new_width
    )
    if train is None:
        train = cfg.phase == "TRAIN"
    return MultibatchLoader(
        dataset, cfg, transformer, train=train, seed=seed, prefetch=prefetch
    )
