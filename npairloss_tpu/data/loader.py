"""The MultibatchData pipeline: sample -> decode (host threads) -> augment
(device, jitted) -> prefetch queue.

The reference's data layer runs decode + augmentation on a CPU prefetch
thread per rank (SURVEY.md §3.5).  Here the host only decodes and
resizes; every augmentation op (warp, crop, mirror, mean) runs on the
accelerator as one jitted graph (``data.transforms``), and a background
thread keeps a bounded queue of ready batches so the training step never
waits on input.
"""

from __future__ import annotations

import logging
import queue
import threading
import weakref
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from npairloss_tpu.config.schema import DataLayerConfig, TransformerConfig
from npairloss_tpu.data.dataset import ArrayDataset, ListFileDataset
from npairloss_tpu.data.sampler import IdentityBalancedSampler
from npairloss_tpu.data.transforms import augment
from npairloss_tpu.resilience import failpoints

log = logging.getLogger("npairloss_tpu.data")


class PrefetchWorkerError(RuntimeError):
    """The prefetch worker died more times than the respawn budget
    allows; carries the failing batch index and respawn count so a
    pod-scale log names *where* the pipeline died, not just that it
    did."""


class _WorkerFailure:
    """Queue marker for a worker death: the exception plus the batch
    index it died on (consumed by ``__next__``, which respawns or
    raises with context)."""

    __slots__ = ("exc", "batch_index")

    def __init__(self, exc: BaseException, batch_index: int):
        self.exc = exc
        self.batch_index = batch_index


def _identity_counts(cfg: DataLayerConfig) -> Tuple[int, int]:
    ids = cfg.identity_num_per_batch
    imgs = cfg.img_num_per_identity
    if not ids or not imgs:
        # Fall back to pairs (the minimum the mining contract allows).
        imgs = imgs or 2
        ids = ids or max(1, (cfg.batch_size or 2) // imgs)
    return ids, imgs


class MultibatchLoader:
    """Iterator of (images[float32 NHWC], labels[int32]) batches."""

    def __init__(
        self,
        dataset,
        cfg: DataLayerConfig,
        transformer: Optional[TransformerConfig] = None,
        train: bool = True,
        seed: int = 0,
        prefetch: int = 2,
        max_worker_restarts: int = 3,
    ):
        self.dataset = dataset
        self.cfg = cfg
        self.transformer = transformer
        self.train = train
        ids, imgs = _identity_counts(cfg)
        self.sampler = IdentityBalancedSampler(
            dataset.labels,
            ids,
            imgs,
            rand_identity=cfg.rand_identity,
            shuffle=cfg.shuffle,
            seed=seed,
        )
        self._key = jax.random.PRNGKey(seed)
        self._queue: queue.Queue = queue.Queue(maxsize=max(prefetch, 1))
        self._stop = threading.Event()
        # Bounded fault tolerance (docs/RESILIENCE.md): a worker death
        # respawns the thread up to ``max_worker_restarts`` CONSECUTIVE
        # times before surfacing a PrefetchWorkerError with the batch
        # context; a successfully delivered batch resets the budget, so
        # sparse transient errors over a multi-day run never accumulate
        # into an abort while a deterministic failure still dies after
        # max_worker_restarts + 1 attempts.
        self.max_worker_restarts = max_worker_restarts
        self._respawns = 0
        self._batch_seq = 0  # written by the (single) worker thread only
        self._spawn_worker()

    def _spawn_worker(self):
        # The worker holds only a weakref to the loader, so an abandoned
        # loader (no close()) is still garbage-collectable; __del__ then
        # stops the thread.
        self._thread = threading.Thread(
            target=_prefetch_worker,
            args=(weakref.ref(self), self._queue, self._stop),
            daemon=True,
        )
        self._thread.start()

    # -- host side: sample + decode (see _prefetch_worker) -----------------

    def _produce_one(self):
        failpoints.fire("data.worker")
        idx = next(self.sampler)
        images = self.dataset.load_batch(idx).astype(np.float32)
        labels = self.dataset.labels[idx].astype(np.int32)
        self._batch_seq += 1
        return images, labels

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        return self

    def __next__(self):
        while True:
            if self._stop.is_set():
                raise StopIteration("loader is closed")
            item = self._queue.get()
            if isinstance(item, _WorkerFailure):
                if self._respawns < self.max_worker_restarts:
                    self._respawns += 1
                    log.warning(
                        "data prefetch worker died at batch %d (%s: %s); "
                        "respawning (%d/%d)",
                        item.batch_index, type(item.exc).__name__,
                        item.exc, self._respawns, self.max_worker_restarts,
                    )
                    self._spawn_worker()
                    continue
                self._stop.set()
                raise PrefetchWorkerError(
                    f"data prefetch worker failed at batch "
                    f"{item.batch_index} after {self._respawns} "
                    f"respawns: {type(item.exc).__name__}: {item.exc}"
                ) from item.exc
            images, labels = item
            self._respawns = 0  # healthy batch: the budget is per-streak
            return _maybe_augment(self, images), labels

    def close(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        # The worker only weakrefs the loader, so this runs even without
        # close(); stop the thread rather than leak it.
        try:
            self._stop.set()
        except AttributeError:
            pass


def _prefetch_worker(loader_ref, q: queue.Queue, stop: threading.Event):
    """Module-level worker holding only a weakref to the loader (plus its
    queue/stop-event, which don't reference back), so an abandoned loader
    is garbage-collectable even while the worker blocks on a full queue."""

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=1.0)
                return True
            except queue.Full:
                continue
        return False

    while not stop.is_set():
        loader = loader_ref()
        if loader is None:
            return
        try:
            item = loader._produce_one()
            fatal = False
        except BaseException as exc:  # surface in __next__, not silently
            # Wrapped with the batch index so the consumer can respawn
            # (bounded) or raise with context instead of a bare error.
            item, fatal = _WorkerFailure(exc, loader._batch_seq), True
        del loader  # no strong ref while blocking on the queue
        if not put(item) or fatal:
            return


class NativeMultibatchLoader:
    """MultibatchLoader on the C++ runtime (``data.native``): sampling,
    decode, resize and batch assembly run in native worker threads off
    the GIL; augmentation stays on-device as one jitted graph."""

    def __init__(
        self,
        cfg: DataLayerConfig,
        transformer: Optional[TransformerConfig] = None,
        train: bool = True,
        seed: int = 0,
        prefetch: int = 2,
        threads: int = 4,
    ):
        from npairloss_tpu.data import native

        self.cfg = cfg
        self.transformer = transformer
        self.train = train
        self._key = jax.random.PRNGKey(seed)
        self.dataset = native.NativeListFileDataset(
            cfg.root_folder, cfg.source, cfg.new_height, cfg.new_width
        )
        ids, imgs = _identity_counts(cfg)
        self._prefetcher = native.NativePrefetcher(
            self.dataset, ids, imgs,
            rand_identity=cfg.rand_identity, shuffle=cfg.shuffle,
            seed=seed, threads=threads, prefetch=prefetch,
        )

    def __iter__(self):
        return self

    def __next__(self):
        images, labels = next(self._prefetcher)
        return _maybe_augment(self, images.astype(np.float32)), labels

    def close(self):
        self._prefetcher.close()
        self.dataset.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _maybe_augment(loader, images):
    """On-device augmentation shared by both loaders: applied only when
    the transform config is non-default or a DataTransformer is set, with
    the loader's own PRNG key chain."""
    if (
        loader.cfg.transform == type(loader.cfg.transform)()
        and loader.transformer is None
    ):
        return images
    loader._key, sub = jax.random.split(loader._key)
    return augment(
        images, sub,
        tp=loader.cfg.transform, transformer=loader.transformer,
        train=loader.train,
    )


def multibatch_loader(
    cfg: DataLayerConfig,
    transformer: Optional[TransformerConfig] = None,
    train: Optional[bool] = None,
    seed: int = 0,
    prefetch: int = 2,
    native: str = "auto",
):
    """Build the full pipeline from a parsed MultibatchData layer config.

    ``native``: "auto" uses the C++ runtime when it is buildable AND the
    config can use it (fixed resize dims — the loader's batch contract);
    "never" forces the Python pipeline; "require" raises when the native
    runtime is unavailable.  Decode-format support differs: native reads
    JPEG (when built against libjpeg — the CUB/SOP case) plus
    PPM/PGM/BMP/NPY-u8; the Python path reads anything PIL does — a
    native worker hitting an unsupported format surfaces the error on
    the next batch, so "auto" keeps Python for such datasets (routing
    samples the first ~4k list entries, see _list_file_all_suffixed).
    """
    if train is None:
        train = cfg.phase == "TRAIN"
    if native not in ("auto", "never", "require"):
        raise ValueError(f"native must be auto/never/require, got {native!r}")
    if native != "never" and cfg.new_height and cfg.new_width:
        from npairloss_tpu.data import native as nd

        available = nd.native_available()  # cached; check before file I/O
        # JPEG routes native only when the build linked libjpeg.
        supported = nd.native_suffixes() if available else ()
        if native == "require" and not available:
            raise RuntimeError("native data runtime unavailable")
        try:
            if available and (
                native == "require"
                or _list_file_all_suffixed(cfg.source, supported)
            ):
                return NativeMultibatchLoader(
                    cfg, transformer, train=train, seed=seed,
                    prefetch=prefetch,
                )
        except OSError:
            pass  # unreadable list file: let the Python path report it
    elif native == "require":
        raise RuntimeError(
            "native loader requires new_height/new_width (fixed batch shape)"
        )
    dataset = ListFileDataset(
        cfg.root_folder, cfg.source, cfg.new_height, cfg.new_width
    )
    return MultibatchLoader(
        dataset, cfg, transformer, train=train, seed=seed, prefetch=prefetch
    )


def shard_batches(
    batches: Iterator[Tuple[np.ndarray, np.ndarray]],
    rank: int,
    count: int,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Per-process disjoint shards of a deterministic pod-global batch
    stream — the multi-controller data model (docs/DISTRIBUTED.md).

    Every controller builds the SAME loader (same list file, same
    seed), so each one computes the identical global batch schedule;
    this wrapper hands process ``rank`` rows
    ``[rank*n : (rank+1)*n]`` of every batch (``n = rows // count``).
    The shards are disjoint by construction, their concatenation in
    rank order IS the global batch (``process_local_batch`` reassembles
    exactly it on the mesh), and the global batch — hence the training
    trajectory — is independent of how many controllers split it: the
    single-process run on the unsliced stream is the bit-identical
    parity oracle.  Mirrors the reference's per-rank MultibatchData
    with a shared schedule (``mpirun -np G``, cu:17-43).

    Loud on a batch whose rows don't divide by ``count`` — a silently
    dropped remainder would change the pool every step.
    """
    if not (0 <= int(rank) < int(count)):
        raise ValueError(f"rank {rank} outside [0, {count})")
    rank, count = int(rank), int(count)

    def gen():
        for inputs, labels in batches:
            rows = len(labels)
            if rows % count:
                raise ValueError(
                    f"global batch of {rows} rows does not divide over "
                    f"{count} processes; fix identity_num_per_batch x "
                    "img_num_per_identity to a multiple of the process "
                    "count")
            n = rows // count
            sl = slice(rank * n, (rank + 1) * n)
            yield np.asarray(inputs)[sl], np.asarray(labels)[sl]

    return gen()


def _list_file_all_suffixed(source: str, suffixes, sample: int = 4096) -> bool:
    """True when the list file's entries all carry a native-decodable
    suffix.  Bounded: only the first ``sample`` entries are examined (an
    O(dataset) pre-scan per loader is not acceptable for million-image
    lists); datasets are overwhelmingly suffix-homogeneous, and a
    mixed-format tail misrouted to the native runtime fails loudly at
    decode time rather than silently.
    """
    seen = 0
    with open(source, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            path = line.rsplit(None, 1)[0].lower()
            if not path.endswith(suffixes):
                return False
            seen += 1
            if seen >= sample:
                break
    return True
