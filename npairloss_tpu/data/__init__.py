"""Data pipeline: identity-balanced sampling, on-device augmentation,
list-file datasets, synthetic clusters (SURVEY.md §3.5, §7.5)."""

from npairloss_tpu.data.dataset import ArrayDataset, ListFileDataset
from npairloss_tpu.data.loader import (
    MultibatchLoader,
    NativeMultibatchLoader,
    PrefetchWorkerError,
    multibatch_loader,
    shard_batches,
)
from npairloss_tpu.data.sampler import IdentityBalancedSampler
from npairloss_tpu.data.synthetic import synthetic_identity_batches
from npairloss_tpu.data.transforms import (
    apply_transform_param,
    augment,
    data_transformer,
)

__all__ = [
    "ArrayDataset",
    "ListFileDataset",
    "MultibatchLoader",
    "NativeMultibatchLoader",
    "PrefetchWorkerError",
    "multibatch_loader",
    "shard_batches",
    "IdentityBalancedSampler",
    "synthetic_identity_batches",
    "apply_transform_param",
    "augment",
    "data_transformer",
]
