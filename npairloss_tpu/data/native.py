"""ctypes binding for the native data runtime (native/npair_data.cpp).

The C++ library is the TPU-side equivalent of the reference's C++
MultibatchData layer (SURVEY.md §1 L1, §3.5): list-file dataset,
identity-balanced sampler, JPEG (system libjpeg)/PPM/BMP/NPY decode +
bilinear resize, and a worker-pool prefetch ring — all off the GIL.  It is compiled on demand
with g++ (no pip deps); when the toolchain or the library is
unavailable, callers fall back to the pure-Python pipeline
(``data.loader``), which has identical contract semantics.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "npair_data.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_BUILD_DIR, "libnpair_data.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_error: Optional[str] = None


def _build() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # Atomic build: compile to a temp name, rename over the target, so
    # concurrent processes never dlopen a half-written .so.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
    os.close(fd)
    base = [
        "g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
        _SRC, "-o", tmp,
    ]
    # First choice links the system libjpeg (JPEG datasets — CUB/SOP —
    # stay native).  Retry without JPEG ONLY on a jpeg-specific link
    # failure (header present, runtime library missing): any other
    # failure must surface, not silently cache a JPEG-less .so forever.
    try:
        subprocess.run(
            base + ["-ljpeg"], check=True, capture_output=True, text=True
        )
        os.replace(tmp, _LIB)
        return _LIB
    except (subprocess.CalledProcessError, FileNotFoundError) as exc:
        stderr = getattr(exc, "stderr", "") or str(exc)
        if "jpeg" not in stderr.lower():
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise RuntimeError(f"native build failed: {stderr}") from exc
        import logging

        logging.getLogger(__name__).warning(
            "libjpeg link failed (%s); rebuilding native runtime without "
            "JPEG — JPEG datasets will use the Python/PIL path",
            stderr.strip().splitlines()[-1] if stderr.strip() else exc,
        )
    try:
        subprocess.run(
            base + ["-DND_NO_JPEG"], check=True, capture_output=True, text=True
        )
        os.replace(tmp, _LIB)
        return _LIB
    except (subprocess.CalledProcessError, FileNotFoundError) as exc:
        if os.path.exists(tmp):
            os.unlink(tmp)
        detail = getattr(exc, "stderr", "") or str(exc)
        raise RuntimeError(f"native build failed: {detail}") from exc


def _load() -> ctypes.CDLL:
    global _lib, _lib_error
    with _lock:
        if _lib is not None:
            return _lib
        if _lib_error is not None:
            raise RuntimeError(_lib_error)
        try:
            # Rebuild when the source is newer; a prebuilt .so without the
            # source on disk is used as-is.
            stale = not os.path.exists(_LIB) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
            )
            if stale:
                _build()
            try:
                lib = ctypes.CDLL(_LIB)
            except OSError:
                # A present-but-unloadable .so (wrong arch/glibc): rebuild
                # from source once rather than caching unavailability.
                if stale or not os.path.exists(_SRC):
                    raise
                _build()
                lib = ctypes.CDLL(_LIB)
        except (OSError, RuntimeError) as exc:
            _lib_error = f"native data runtime unavailable: {exc}"
            raise RuntimeError(_lib_error) from exc
        lib.nd_last_error.restype = ctypes.c_char_p
        lib.nd_has_jpeg.restype = ctypes.c_int
        lib.nd_dataset_dims.restype = ctypes.c_int
        lib.nd_dataset_dims.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ]
        lib.nd_dataset_open.restype = ctypes.c_void_p
        lib.nd_dataset_open.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_longlong),
        ]
        lib.nd_dataset_labels.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong)]
        lib.nd_dataset_load.restype = ctypes.c_int
        lib.nd_dataset_load.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_ubyte),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ]
        lib.nd_dataset_close.argtypes = [ctypes.c_void_p]
        lib.nd_loader_create.restype = ctypes.c_void_p
        lib.nd_loader_create.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_ulonglong, ctypes.c_int, ctypes.c_int,
        ]
        lib.nd_loader_next.restype = ctypes.c_int
        lib.nd_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ubyte),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.nd_loader_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def native_available() -> bool:
    """True when the compiled runtime can be (or was) loaded."""
    try:
        _load()
        return True
    except RuntimeError:
        return False


def native_jpeg_supported() -> bool:
    """True when the compiled runtime decodes JPEG (linked libjpeg)."""
    try:
        return bool(_load().nd_has_jpeg())
    except RuntimeError:
        return False


def native_suffixes() -> Tuple[str, ...]:
    """Image-file suffixes the loaded native runtime decodes itself —
    the routing contract for data.loader.multibatch_loader."""
    base = (".ppm", ".pgm", ".bmp", ".npy")
    if native_jpeg_supported():
        return base + (".jpg", ".jpeg")
    return base


def _err(lib) -> str:
    return lib.nd_last_error().decode("utf-8", "replace")


class NativeListFileDataset:
    """Native-decode counterpart of ``data.dataset.ListFileDataset``:
    same "relative/path label" list contract, decode in C++
    (JPEG when built with libjpeg, PPM/PGM/BMP/NPY-u8),
    OpenCV-convention bilinear resize."""

    def __init__(self, root_folder: str, source: str,
                 new_height: int = 0, new_width: int = 0):
        self._lib = _load()
        n = ctypes.c_longlong()
        self._handle = self._lib.nd_dataset_open(
            root_folder.encode(), source.encode(),
            int(new_height), int(new_width), ctypes.byref(n),
        )
        if not self._handle:
            raise RuntimeError(_err(self._lib))
        self._n = int(n.value)
        self.new_height = int(new_height)
        self.new_width = int(new_width)
        labels = np.empty(self._n, np.int64)
        self._lib.nd_dataset_labels(
            self._handle,
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        )
        self.labels = labels

    def __len__(self) -> int:
        return self._n

    def dims(self, index: int) -> Tuple[int, int]:
        """(h, w) of the item's output buffer before loading: the fixed
        resize dims, or the decoded native dims when unset."""
        if self._handle is None:
            raise RuntimeError("dataset is closed")
        oh, ow = ctypes.c_int(), ctypes.c_int()
        rc = self._lib.nd_dataset_dims(
            self._handle, int(index), ctypes.byref(oh), ctypes.byref(ow)
        )
        if rc != 0:
            raise RuntimeError(_err(self._lib))
        return int(oh.value), int(ow.value)

    def load(self, index: int) -> np.ndarray:
        if self._handle is None:
            raise RuntimeError("dataset is closed")
        if not (self.new_height and self.new_width):
            raise ValueError(
                "load() without new_height/new_width needs variable-size "
                "buffers; set the resize dims (the MultibatchData contract)"
            )
        out = np.empty((self.new_height, self.new_width, 3), np.uint8)
        oh, ow = ctypes.c_int(), ctypes.c_int()
        rc = self._lib.nd_dataset_load(
            self._handle, int(index),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            ctypes.byref(oh), ctypes.byref(ow),
        )
        if rc != 0:
            raise RuntimeError(_err(self._lib))
        return out

    def load_batch(self, indices) -> np.ndarray:
        return np.stack([self.load(int(i)) for i in indices])

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.nd_dataset_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativePrefetcher:
    """Iterator of (uint8 images [B,H,W,3], int32 labels [B]) batches,
    produced by the C++ worker pool — sampling, decode, resize and batch
    assembly all run off the GIL."""

    def __init__(self, dataset: NativeListFileDataset,
                 identity_num_per_batch: int, img_num_per_identity: int,
                 rand_identity: bool = True, shuffle: bool = True,
                 seed: int = 0, threads: int = 2, prefetch: int = 2):
        self._ds = dataset  # keep alive: loader holds a raw pointer
        self._lib = dataset._lib
        self.batch_size = identity_num_per_batch * img_num_per_identity
        self.h, self.w = dataset.new_height, dataset.new_width
        self._handle = self._lib.nd_loader_create(
            dataset._handle, int(identity_num_per_batch),
            int(img_num_per_identity), int(bool(rand_identity)),
            int(bool(shuffle)), int(seed), int(threads), int(prefetch),
        )
        if not self._handle:
            raise RuntimeError(_err(self._lib))

    def __iter__(self):
        return self

    def __next__(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._handle is None:
            raise StopIteration("loader is closed")
        images = np.empty((self.batch_size, self.h, self.w, 3), np.uint8)
        labels = np.empty(self.batch_size, np.int32)
        rc = self._lib.nd_loader_next(
            self._handle,
            images.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        )
        if rc != 0:
            raise RuntimeError(_err(self._lib))
        return images, labels

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.nd_loader_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
