"""On-device, jit-compiled data augmentation.

The reference splits augmentation between Caffe's ``transform_param``
(mean subtraction, random crop, mirror — usage/def.prototxt:10-16) and a
``DataTransformer`` layer doing geometric warps (rotation, translation,
scale, horizontal flip, optional elastic deformation —
def.prototxt:69-83), all on CPU per image inside the data prefetch
thread.

TPU-first redesign: the whole augmentation stack is ONE jitted, batched
function on device —

  * rotation/scale/translation compose into a single inverse affine
    matrix per image; one bilinear gather warps the image (no per-op
    passes over HBM);
  * the elastic deformation is a Gaussian-smoothed random displacement
    field added to the same sampling grid, so it fuses into the same
    gather;
  * crop/mirror/mean-subtract are elementwise/slice ops XLA fuses into
    the surrounding graph.

Everything is shape-static and batched (vmap), so XLA tiles it onto the
VPU; host work is reduced to decode + resize.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from npairloss_tpu.config.schema import TransformParam, TransformerConfig


# ---------------------------------------------------------------------------
# Bilinear warp primitives
# ---------------------------------------------------------------------------


def _bilinear_sample(img: jax.Array, ys: jax.Array, xs: jax.Array) -> jax.Array:
    """Sample img[H,W,C] at float coords (ys, xs) [H,W], border-clamped."""
    h, w = img.shape[0], img.shape[1]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = ys - y0
    wx = xs - x0
    y0 = jnp.clip(y0.astype(jnp.int32), 0, h - 1)
    x0 = jnp.clip(x0.astype(jnp.int32), 0, w - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)

    def at(yy, xx):
        return img[yy, xx]

    top = at(y0, x0) * (1 - wx)[..., None] + at(y0, x1) * wx[..., None]
    bot = at(y1, x0) * (1 - wx)[..., None] + at(y1, x1) * wx[..., None]
    return top * (1 - wy)[..., None] + bot * wy[..., None]


def _gaussian_kernel1d(radius: float, width: int) -> np.ndarray:
    sigma = max(float(radius), 1e-3)
    xs = np.arange(-width, width + 1, dtype=np.float32)
    k = np.exp(-0.5 * (xs / sigma) ** 2)
    return (k / k.sum()).astype(np.float32)


def _smooth_field(field: jax.Array, kernel: jax.Array) -> jax.Array:
    """Separable Gaussian blur of a [H,W] field."""
    pad = kernel.shape[0] // 2
    f = jnp.pad(field, ((pad, pad), (0, 0)), mode="edge")
    f = jax.vmap(lambda col: jnp.convolve(col, kernel, mode="valid"),
                 in_axes=1, out_axes=1)(f)
    f = jnp.pad(f, ((0, 0), (pad, pad)), mode="edge")
    f = jax.vmap(lambda row: jnp.convolve(row, kernel, mode="valid"))(f)
    return f


# ---------------------------------------------------------------------------
# DataTransformer: rotation + translation + scale + flip + elastic
# ---------------------------------------------------------------------------


def _warp_one(
    img: jax.Array,
    angle: jax.Array,
    tx: jax.Array,
    ty: jax.Array,
    sx: jax.Array,
    sy: jax.Array,
    flip: jax.Array,
    disp: Optional[Tuple[jax.Array, jax.Array]],
) -> jax.Array:
    """Apply the inverse affine (about the image center) + displacement."""
    h, w = img.shape[0], img.shape[1]
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    yy, xx = jnp.meshgrid(
        jnp.arange(h, dtype=jnp.float32),
        jnp.arange(w, dtype=jnp.float32),
        indexing="ij",
    )
    # Output pixel -> input pixel: undo translation, then rotation+scale
    # about the center, then optional horizontal flip.
    yr = yy - cy - ty
    xr = xx - cx - tx
    cos, sin = jnp.cos(angle), jnp.sin(angle)
    xs = (cos * xr + sin * yr) / sx
    ys = (-sin * xr + cos * yr) / sy
    xs = jnp.where(flip, -xs, xs)
    ys = ys + cy
    xs = xs + cx
    if disp is not None:
        ys = ys + disp[0]
        xs = xs + disp[1]
    return _bilinear_sample(img, ys, xs)


@functools.partial(jax.jit, static_argnames=("cfg",))
def data_transformer(
    images: jax.Array, key: jax.Array, cfg: TransformerConfig
) -> jax.Array:
    """Batched geometric augmentation per the DataTransformer layer.

    Parameter semantics (def.prototxt:69-83): per image, draw
      angle ~ U(-rotate_angle_scope, +rotate_angle_scope)       [radians]
      t_w   ~ U(-translation_w_scope, +translation_w_scope)     [pixels]
      t_h   ~ U(-translation_h_scope, +translation_h_scope)
      s_w   ~ U(min(1, 1/scale_w_scope), max(1, scale_w_scope))
      s_h   ~ U(min(1, 1/scale_h_scope), max(1, scale_h_scope))
      flip  ~ Bernoulli(0.5) when h_flip
    plus, when elastic_transform, a displacement field of N(0, amplitude²)
    noise smoothed by a Gaussian of sigma ``radius``.
    """
    n, h, w = images.shape[0], images.shape[1], images.shape[2]
    images = images.astype(jnp.float32)
    ks = jax.random.split(key, 7)

    scope = float(cfg.rotate_angle_scope)
    angles = jax.random.uniform(ks[0], (n,), minval=-scope, maxval=scope)
    txs = jax.random.uniform(
        ks[1], (n,),
        minval=-float(cfg.translation_w_scope),
        maxval=float(cfg.translation_w_scope),
    )
    tys = jax.random.uniform(
        ks[2], (n,),
        minval=-float(cfg.translation_h_scope),
        maxval=float(cfg.translation_h_scope),
    )

    def scale_range(s):
        s = float(s) if s else 1.0
        if s <= 0:
            return 1.0, 1.0
        # Symmetric zoom range U(min(s,1/s), max(s,1/s)); scope 0.8 and
        # scope 1.25 both mean the same +-25% zoom.
        return min(s, 1.0 / s), max(s, 1.0 / s)

    lo_w, hi_w = scale_range(cfg.scale_w_scope)
    lo_h, hi_h = scale_range(cfg.scale_h_scope)
    sxs = jax.random.uniform(ks[3], (n,), minval=lo_w, maxval=hi_w)
    sys_ = jax.random.uniform(ks[4], (n,), minval=lo_h, maxval=hi_h)
    flips = (
        jax.random.bernoulli(ks[5], 0.5, (n,))
        if cfg.h_flip
        else jnp.zeros((n,), bool)
    )

    if cfg.elastic_transform:
        kernel = jnp.asarray(
            _gaussian_kernel1d(cfg.radius, max(int(3 * cfg.radius), 1))
        )
        noise = (
            jax.random.normal(ks[6], (n, 2, h, w), dtype=jnp.float32)
            * jnp.float32(cfg.amplitude)
        )
        smooth = jax.vmap(jax.vmap(lambda f: _smooth_field(f, kernel)))(noise)
        disp = (smooth[:, 0], smooth[:, 1])
        return jax.vmap(_warp_one)(
            images, angles, txs, tys, sxs, sys_, flips, disp
        )
    return jax.vmap(
        lambda i, a, tx, ty, sx, sy, f: _warp_one(i, a, tx, ty, sx, sy, f, None)
    )(images, angles, txs, tys, sxs, sys_, flips)


# ---------------------------------------------------------------------------
# transform_param: mean subtraction + random crop + mirror
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("tp", "train"))
def apply_transform_param(
    images: jax.Array, key: jax.Array, tp: TransformParam, train: bool = True
) -> jax.Array:
    """Caffe transform_param semantics, batched on device.

    Mean values appear in the prototxt in Caffe's BGR channel order
    (def.prototxt:13-15: 104, 117, 123); images here are RGB, so the mean
    triple is reversed before subtraction.  TRAIN crops at a random
    offset and mirrors with p=0.5 per image; TEST center-crops without
    mirroring (standard Caffe DataTransformer behavior).
    """
    images = images.astype(jnp.float32)
    n, h, w, c = images.shape

    if tp.mean_value:
        mean = list(tp.mean_value)
        if len(mean) == 1:
            mean = mean * c
        if len(mean) != c:
            raise ValueError(
                f"mean_value has {len(tp.mean_value)} entries; expected 1 or "
                f"{c} (channel count)"
            )
        mean = mean[::-1]
        images = images - jnp.asarray(mean, jnp.float32)[None, None, None, :]

    if tp.scale != 1.0:
        images = images * jnp.float32(tp.scale)

    crop = int(tp.crop_size)
    if crop and crop > min(h, w):
        raise ValueError(f"crop_size {crop} exceeds image size {h}x{w}")
    if crop and (crop < h or crop < w):
        kh, kw, km = jax.random.split(key, 3)
        if train:
            oy = jax.random.randint(kh, (n,), 0, h - crop + 1)
            ox = jax.random.randint(kw, (n,), 0, w - crop + 1)
        else:
            oy = jnp.full((n,), (h - crop) // 2, jnp.int32)
            ox = jnp.full((n,), (w - crop) // 2, jnp.int32)
        images = jax.vmap(
            lambda im, y, x: jax.lax.dynamic_slice(
                im, (y, x, 0), (crop, crop, c)
            )
        )(images, oy, ox)
    else:
        km = key

    if tp.mirror and train:
        do = jax.random.bernoulli(km, 0.5, (n,))
        images = jnp.where(do[:, None, None, None], images[:, :, ::-1, :], images)
    return images


def augment(
    images: jax.Array,
    key: jax.Array,
    tp: Optional[TransformParam] = None,
    transformer: Optional[TransformerConfig] = None,
    train: bool = True,
) -> jax.Array:
    """Full augmentation pipeline: DataTransformer warp (TRAIN only, as in
    the reference's include{phase:TRAIN}) then transform_param."""
    k1, k2 = jax.random.split(key)
    if transformer is not None and train:
        images = data_transformer(images, k1, transformer)
    if tp is not None:
        images = apply_transform_param(images, k2, tp, train)
    return images
