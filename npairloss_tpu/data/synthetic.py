"""Synthetic identity-balanced data — for tests, smoke runs and benchmarks.

Honors the MultibatchData batch contract (identity_num_per_batch x
img_num_per_identity, def.prototxt:25-27): every query has exactly
img_num_per_identity - 1 in-batch positives, the invariant the mining
statistics rely on (SURVEY.md §3.5).
"""

from __future__ import annotations

from typing import Iterator, Sequence, Tuple

import numpy as np


def synthetic_identity_batches(
    num_identities: int,
    identity_num_per_batch: int,
    img_num_per_identity: int,
    input_shape: Sequence[int],
    noise: float = 0.5,
    seed: int = 0,
    num_classes_total: int | None = None,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yields (inputs, labels): inputs are per-identity Gaussian clusters."""
    rng = np.random.default_rng(seed)
    total = num_classes_total or num_identities
    dim = int(np.prod(input_shape))
    centers = rng.standard_normal((total, dim)).astype(np.float32)
    while True:
        ids = rng.choice(total, size=identity_num_per_batch, replace=False)
        labels = np.repeat(ids, img_num_per_identity).astype(np.int32)
        x = centers[labels] + noise * rng.standard_normal(
            (len(labels), dim)
        ).astype(np.float32)
        yield x.reshape(len(labels), *input_shape), labels
