"""Datasets: the MultibatchData list-file contract + in-memory arrays.

The reference's (external) MultibatchData layer reads ``root_folder`` +
``source`` — a text file of ``relative/path label`` lines — decodes and
resizes each image to ``new_height`` x ``new_width``
(usage/def.prototxt:17-24).  ``ListFileDataset`` reproduces that contract
on the host (PIL decode, one thread per prefetch worker);
``ArrayDataset`` serves in-memory arrays with the same interface for
tests and synthetic runs.
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

import numpy as np


class ListFileDataset:
    """``source`` list file of "path label" rows under ``root_folder``."""

    def __init__(
        self,
        root_folder: str,
        source: str,
        new_height: int = 0,
        new_width: int = 0,
    ):
        self.root = root_folder
        self.new_height = int(new_height)
        self.new_width = int(new_width)
        self.paths: List[str] = []
        labels: List[int] = []
        with open(source, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                # "path label"; paths may contain spaces — label is the
                # last whitespace-separated token (space or tab).
                parts = line.rsplit(None, 1)
                if len(parts) != 2:
                    raise ValueError(f"malformed list line: {line!r}")
                path, lbl = parts
                self.paths.append(path)
                labels.append(int(float(lbl)))
        self.labels = np.asarray(labels, dtype=np.int64)

    def __len__(self) -> int:
        return len(self.paths)

    def load(self, index: int) -> np.ndarray:
        """Decode one image to uint8 RGB [new_h, new_w, 3]."""
        from PIL import Image

        path = os.path.join(self.root, self.paths[index])
        with Image.open(path) as im:
            im = im.convert("RGB")
            if self.new_height and self.new_width:
                im = im.resize(
                    (self.new_width, self.new_height), Image.BILINEAR
                )
            return np.asarray(im, dtype=np.uint8)

    def load_batch(self, indices: Sequence[int]) -> np.ndarray:
        return np.stack([self.load(int(i)) for i in indices])


class ArrayDataset:
    """In-memory images+labels with the ListFileDataset interface."""

    def __init__(self, images: np.ndarray, labels: np.ndarray):
        assert len(images) == len(labels)
        self.images = images
        self.labels = np.asarray(labels, dtype=np.int64)

    def __len__(self) -> int:
        return len(self.images)

    def load(self, index: int) -> np.ndarray:
        return self.images[index]

    def load_batch(self, indices: Sequence[int]) -> np.ndarray:
        return self.images[np.asarray(indices)]
