"""Identity-balanced batch sampling — the MultibatchData contract.

The reference's data layer builds every batch as ``identity_num_per_batch``
identities x ``img_num_per_identity`` images (usage/def.prototxt:25-27,
SURVEY.md §3.5).  This is load-bearing for the loss: it guarantees every
query has img_num_per_identity - 1 in-batch positives locally (and
2G - 1 globally), which the mining statistics assume (reference:
npair_multi_class_loss.cu:243-250 expects non-empty ident lists).

``rand_identity`` picks identities uniformly at random each batch;
otherwise identities cycle in (shuffled) order.  Images within an identity
are drawn without replacement until the identity's pool is exhausted, then
reshuffled — with replacement only when an identity has fewer images than
``img_num_per_identity``.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence

import numpy as np


class IdentityBalancedSampler:
    """Yields index batches of shape [ids_per_batch * imgs_per_id]."""

    def __init__(
        self,
        labels: Sequence[int],
        identity_num_per_batch: int,
        img_num_per_identity: int,
        rand_identity: bool = True,
        shuffle: bool = True,
        seed: int = 0,
    ):
        labels = np.asarray(labels)
        self.by_identity: Dict[int, np.ndarray] = {}
        for lbl in np.unique(labels):
            self.by_identity[int(lbl)] = np.flatnonzero(labels == lbl)
        self.identities = np.array(sorted(self.by_identity), dtype=np.int64)
        if len(self.identities) < identity_num_per_batch:
            raise ValueError(
                f"need >= {identity_num_per_batch} identities, have "
                f"{len(self.identities)}"
            )
        self.ids_per_batch = int(identity_num_per_batch)
        self.imgs_per_id = int(img_num_per_identity)
        self.rand_identity = bool(rand_identity)
        self.shuffle = bool(shuffle)
        self.rng = np.random.default_rng(seed)
        # Per-identity draw-without-replacement cursors.
        self._pools: Dict[int, List[int]] = {}
        # Sequential identity cursor for rand_identity=false.
        self._id_order = self.identities.copy()
        if self.shuffle:
            self.rng.shuffle(self._id_order)
        self._id_pos = 0

    def _draw_images(self, identity: int) -> List[int]:
        pool = self.by_identity[identity]
        if len(pool) < self.imgs_per_id:
            # Degenerate identity: sample with replacement (the batch
            # contract must hold for the mining statistics).
            return list(self.rng.choice(pool, size=self.imgs_per_id))
        out: List[int] = []
        while len(out) < self.imgs_per_id:
            cached = self._pools.get(identity)
            if not cached:
                # Refill, excluding this batch's picks so a group never
                # contains the same image twice (the loss would see a
                # zero-distance positive and skew the mining statistics).
                cached = [int(i) for i in pool if int(i) not in out]
                if self.shuffle:
                    self.rng.shuffle(cached)
                self._pools[identity] = cached
            out.append(int(cached.pop()))
        return out

    def _next_identities(self) -> np.ndarray:
        if self.rand_identity:
            return self.rng.choice(
                self.identities, size=self.ids_per_batch, replace=False
            )
        chosen: List[int] = []
        while len(chosen) < self.ids_per_batch:
            if self._id_pos >= len(self._id_order):
                self._id_pos = 0
                if self.shuffle:
                    self.rng.shuffle(self._id_order)
            cand = int(self._id_order[self._id_pos])
            self._id_pos += 1
            # A mid-batch wrap + reshuffle may resurface an identity this
            # batch already holds; skip it to keep batch identities
            # distinct (the contract the mining statistics assume).
            if cand not in chosen:
                chosen.append(cand)
        return np.array(chosen)

    def __iter__(self) -> Iterator[np.ndarray]:
        return self

    def __next__(self) -> np.ndarray:
        idx: List[int] = []
        for identity in self._next_identities():
            idx.extend(self._draw_images(int(identity)))
        return np.array(idx, dtype=np.int64)
