"""Ring-blockwise N-pair loss: pod-scale negative pools without the matrix.

The reference materializes the full N x (N*G) pair-similarity matrix after
an MPI_Allgather of every rank's embeddings (reference:
npair_multi_class_loss.cu:17-43, cu:218).  That is O(N^2 G) memory per
rank — fine at G=8, fatal for the 32k-batch stretch config
(BASELINE.json) where the gathered pool no longer fits HBM.

This module is the contrastive-learning transplant of ring attention
(SURVEY.md §5.7): instead of gathering the pool, each shard's feature
block circulates around the mesh axis via ``jax.lax.ppermute`` while
every shard streams its N x N_block similarity tile on the MXU,
reducing online.  Memory is O(N x N_block); the interconnect carries
each block exactly G-1 hops per pass, and XLA overlaps the ppermute
with the tile matmul.

Three ring passes per step:

  1. **stats**: per-query min-within / max-between / max-all running
     reductions (the mining statistics of cu:229-265) — plus running
     top-(k+1) similarity/label lists for Recall@k.
  2. **loss**: selection mask from the absolute thresholds, stabilized
     exp, running I_q/D_q sums (cu:343-388 semantics).
  3. **backward**: the weight tile w = (-p1+p2+p3)*g/N is recomputed
     per block; the query-role grad accumulates locally while the
     database-role grad rides the ring WITH its feature block, arriving
     at the block's owner as the full cross-shard sum — exactly what the
     reference's MPI_Allreduce produces (cu:462-489) — then merged
     0.5/0.5 with the query-role grad (cu:492-497).

Mining-method support: ALL methods are exact.  Absolute (HARD / EASY /
RAND) thresholds are streamed min/max reductions.  RELATIVE_* needs
rank statistics over the full pair population — the reference sorts the
whole N x (N*G) block on the host (cu:266-273); here the k-th smallest
masked pair value is recovered EXACTLY by MSD radix selection over
sortable float bit-keys: NUM_DIGITS ring passes, each histogramming one
RADIX_BITS-bit digit of the monotone uint32 key via scatter-free
compare-and-reduce, narrow to the target element's exact bit pattern
(SURVEY.md §7's "distributed top-k" growth path).  When both sides
are relative, that costs NUM_DIGITS-1 extra passes total — the digit-0
histogram rides the stats pass for free, and later digits share one
pass across sides.  When only the POSITIVE side is relative (the
flagship def.prototxt config), the sparse-positive fast path applies:
identity-balanced sampling gives each query only a handful of
positives, so the stats pass keeps a K-slot buffer of the largest
same-label sims and the AP threshold is an N x K sort — ZERO extra
ring passes, with a mesh-uniform runtime fallback to radix selection
for labels that overflow the buffer.

Memory is O(N x N_block) with ``sim_cache=False``.  By default
(``sim_cache=None``) the engine keeps this shard's (G, N, N) fp32
slice of the pair matrix from the stats pass whenever it fits under
``SIM_CACHE_AUTO_BYTES`` — the later passes then replay the cached
tiles (the radix/loss passes with NO ppermute and no matmul recompute,
the backward ring reusing tiles while the gradient still travels), at
the cost of holding that slice through the step (and through the
model backward, via the VJP residuals).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from npairloss_tpu.parallel._compat import axis_size, pvary
from npairloss_tpu.ops.npair_loss import (
    FLT_MAX,
    SIM_CACHE_AUTO_BYTES,
    resolve_sim_cache_auto,
    MiningMethod,
    MiningRegion,
    NPairLossConfig,
    _clamp_negative,
    _relative_pos,
    absolute_thresholds,
    active_matmul_precision,
    matmul_precision_ctx,
    selection_mask,
    topk_relative_threshold,
)
from npairloss_tpu.ops.rank_select import (
    NUM_DIGITS,
    RADIX_BINS,
    masked_digit_hist,
    population_count_dtype,
    radix_begin,
    radix_finish,
    radix_update,
)

_RELATIVE = (MiningMethod.RELATIVE_HARD, MiningMethod.RELATIVE_EASY)


def ring_supported(cfg: NPairLossConfig) -> bool:
    """Every mining configuration streams (RELATIVE_* via radix select)."""
    return True


def _check_cfg(cfg: NPairLossConfig) -> None:
    pass  # all configs supported; kept for API stability


# Every ring gemm (sim tiles + the two gradient-role gemms) reads the
# trace-time precision ContextVar shared with the other engines —
# see ops.npair_loss.matmul_precision_ctx / active_matmul_precision.
_precision_ctx = matmul_precision_ctx


def _tile(
    feats: jax.Array, block_f: jax.Array
) -> jax.Array:
    """One N x N_block similarity tile on the MXU, fp32 accumulate."""
    return jnp.dot(
        feats,
        block_f.T,
        preferred_element_type=jnp.float32,
        precision=active_matmul_precision(),
    )


def _block_masks(
    labels: jax.Array,
    block_labels: jax.Array,
    my_rank: jax.Array,
    block_rank: jax.Array,
    n_local: int,
) -> Tuple[jax.Array, jax.Array]:
    """same/diff masks for one tile; self-pair excluded when the tile is
    this shard's own block (cu:54 semantics on the tiled grid)."""
    same_lbl = labels[:, None] == block_labels[None, :]
    eye = jnp.eye(n_local, dtype=bool)
    self_pair = jnp.where(my_rank == block_rank, eye, jnp.zeros_like(eye))
    same = same_lbl & ~self_pair
    diff = (~same_lbl) & ~self_pair
    return same, diff


def _pvary(tree, axis_name: str):
    """Mark fresh (replicated) carry values as device-varying so the scan
    carry type stays stable under shard_map's manual-axes tracking."""
    return jax.tree_util.tree_map(
        lambda x: pvary(x, (axis_name,)), tree
    )


def _ring_scan(axis_name: str, body, carry, rotating):
    """Run ``body(carry, rotating, step) -> (carry, rotating)`` G times,
    ppermuting ``rotating`` one hop forward between steps.  Shard r
    therefore sees block (r - step) mod G at step ``step``; after G hops
    every rotating value is back at its owner."""
    g = axis_size(axis_name)
    perm = [(i, (i + 1) % g) for i in range(g)]
    carry = _pvary(carry, axis_name)

    def step_fn(state, step):
        carry, rotating = state
        carry, rotating = body(carry, rotating, step)
        # comm/ scope = the fleet observatory's exchange-path marker
        # (obs.fleet.comms): the hop's collective-permutes carry it in
        # their HLO op_name metadata; the program itself is unchanged.
        with jax.named_scope("comm/ppermute"):
            rotating = jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, axis_name, perm), rotating
            )
        return (carry, rotating), None

    (carry, rotating), _ = jax.lax.scan(
        step_fn, (carry, rotating), jnp.arange(g)
    )
    return carry, rotating


def _cache_scan(cache, accum, carry, axis_name: str):
    """Replay the cached hop tiles locally — ``accum(carry, sims,
    block_labels, block_rank) -> carry`` over the stats pass's hop order.
    No sim recompute, no ppermute: the pass costs one stream of the
    cached slice."""
    def step_fn(c, inp):
        sims, bl, br = inp
        return accum(c, sims, bl, br), None

    carry, _ = jax.lax.scan(
        step_fn, _pvary(carry, axis_name),
        (cache["sims_cache"], cache["labels_cache"], cache["rank_cache"]),
    )
    return carry


# ---------------------------------------------------------------------------
# Pass 1: mining statistics + retrieval top-k
# ---------------------------------------------------------------------------


def _stats_pass(
    feats, labels, my_rank, axis_name: str, top_k_max: int,
    hist0_same: bool = False, hist0_diff: bool = False,
    emit_sims: bool = False, topk_same_k: int = 0,
):
    """Mining statistics in one ring pass; optionally also the digit-0
    radix histograms for RELATIVE_* sides — digit 0 needs no prefix, so
    accumulating it here saves one whole ring pass per relative side —
    and optionally the per-shard similarity cache: the (G, N, N) stack
    of this shard's sim tiles in hop order, plus each hop's block labels
    and rank.  The rotation schedule is deterministic (shard r sees
    block (r - s) mod G at step s), so every later pass can replay the
    cache instead of recomputing tiles — and the selection/loss passes
    then need no ppermute at all."""
    n_local = feats.shape[0]
    g = axis_size(axis_name)
    neg = jnp.float32(-FLT_MAX)
    pos = jnp.float32(FLT_MAX)
    zero_prefix = jnp.zeros((n_local,), jnp.uint32)

    carry = {
        "min_within": jnp.full((n_local,), pos),
        "max_between": jnp.full((n_local,), neg),
        "max_all": jnp.full((n_local,), neg),
        # Pair-population sizes per query, for RELATIVE rank targets
        # (the list sizes of cu:266-273).
        "count_same": jnp.zeros((n_local,), jnp.int32),
        "count_diff": jnp.zeros((n_local,), jnp.int32),
        # Running top-(k+1) non-self sims and a same-label flag for each,
        # for the Recall@k threshold semantics (cu:190-197).
        "top_sims": jnp.full((n_local, top_k_max + 1), neg),
        "top_same": jnp.zeros((n_local, top_k_max + 1), bool),
    }
    if hist0_same:
        carry["hist0_same"] = jnp.zeros((n_local, RADIX_BINS), jnp.int32)
    if hist0_diff:
        carry["hist0_diff"] = jnp.zeros((n_local, RADIX_BINS), jnp.int32)
    if topk_same_k:
        # Sparse-positive fast path: the K largest same-label sims per
        # query, maintained across hops (values are the SAME tile sims
        # the stats/histograms read, so thresholds built from the buffer
        # are bit-identical to radix selection over the ring).
        carry["topk_same"] = jnp.full((n_local, topk_same_k), neg)
    if emit_sims:
        carry["sims_cache"] = jnp.zeros((g, n_local, n_local), jnp.float32)
        carry["labels_cache"] = jnp.zeros((g,) + labels.shape, labels.dtype)
        carry["rank_cache"] = jnp.zeros((g,), jnp.int32)
    rotating = {
        "f": feats,
        "l": labels,
        "rank": my_rank,
    }

    def body(c, rot, step):
        sims = _tile(feats, rot["f"])
        same, diff = _block_masks(labels, rot["l"], my_rank, rot["rank"], n_local)
        c = dict(c)
        if emit_sims:
            c["sims_cache"] = c["sims_cache"].at[step].set(sims)
            c["labels_cache"] = c["labels_cache"].at[step].set(rot["l"])
            c["rank_cache"] = c["rank_cache"].at[step].set(rot["rank"])
        c["min_within"] = jnp.minimum(
            c["min_within"], jnp.where(same, sims, pos).min(axis=1)
        )
        c["max_between"] = jnp.maximum(
            c["max_between"], jnp.where(diff, sims, neg).max(axis=1)
        )
        c["max_all"] = jnp.maximum(
            c["max_all"], jnp.where(same | diff, sims, neg).max(axis=1)
        )
        c["count_same"] = c["count_same"] + same.sum(axis=1, dtype=jnp.int32)
        c["count_diff"] = c["count_diff"] + diff.sum(axis=1, dtype=jnp.int32)
        if hist0_same:
            c["hist0_same"] = c["hist0_same"] + masked_digit_hist(
                sims, same, zero_prefix, 0
            )
        if hist0_diff:
            c["hist0_diff"] = c["hist0_diff"] + masked_digit_hist(
                sims, diff, zero_prefix, 0
            )
        if topk_same_k:
            c["topk_same"] = jax.lax.top_k(
                jnp.concatenate(
                    [c["topk_same"], jnp.where(same, sims, neg)], axis=1
                ),
                topk_same_k,
            )[0]
        nonself = same | diff
        cat_sims = jnp.concatenate(
            [c["top_sims"], jnp.where(nonself, sims, neg)], axis=1
        )
        cat_same = jnp.concatenate([c["top_same"], same], axis=1)
        top_sims, idx = jax.lax.top_k(cat_sims, c["top_sims"].shape[1])
        c["top_sims"] = top_sims
        c["top_same"] = jnp.take_along_axis(cat_same, idx, axis=1)
        return c, rot

    carry, _ = _ring_scan(axis_name, body, carry, rotating)
    return carry


# ---------------------------------------------------------------------------
# Streamed RELATIVE thresholds: exact MSD radix selection over the ring
# ---------------------------------------------------------------------------


def _multi_digit_hist_pass(
    feats, labels, my_rank, axis_name: str, sides, digit: int, cache=None,
):
    """One pass accumulating masked digit histograms for EVERY active
    RELATIVE side at once — the N x N_block sim tile (the expensive
    part) is computed once and feeds both masks.  With the similarity
    cache the pass is a LOCAL scan over the cached tiles (no sim
    recompute, no ppermute); without it, one ring rotation.

    ``sides``: dict side-name -> (use_same, prefix).
    Returns dict side-name -> int32 [N, RADIX_BINS].
    """
    n_local = feats.shape[0]
    carry = {s: jnp.zeros((n_local, RADIX_BINS), jnp.int32) for s in sides}

    def accum(c, sims, blk_labels, blk_rank):
        same, diff = _block_masks(
            labels, blk_labels, my_rank, blk_rank, n_local
        )
        c = dict(c)
        for s, (use_same, prefix) in sides.items():
            mask = same if use_same else diff
            c[s] = c[s] + masked_digit_hist(sims, mask, prefix, digit)
        return c

    if cache is not None:
        return _cache_scan(cache, accum, carry, axis_name)

    rotating = {"f": feats, "l": labels, "rank": my_rank}

    def body(c, rot, step):
        return accum(c, _tile(feats, rot["f"]), rot["l"], rot["rank"]), rot

    carry, _ = _ring_scan(axis_name, body, carry, rotating)
    return carry


def _ring_thresholds(
    feats, labels, my_rank, axis_name: str, cfg: NPairLossConfig, stats,
    cache=None,
):
    """(pos_thr, neg_thr) for any mining config: absolute from streamed
    min/max stats, RELATIVE_* via exact stepwise radix selection.

    Reproduces the dense ``_local/_global_relative_threshold`` semantics
    (ascending sort + ``_relative_pos`` index + ``< 0 -> -FLT_MAX``
    clamp, reference cu:275-337) without the pair matrix.  GLOBAL region
    ranks over this rank's whole flattened N x (N*G) block (cu:296,
    cu:327), LOCAL per query; block populations beyond 2^31 pairs use
    64-bit counts (requires jax_enable_x64) or fail loudly at trace
    time — int32 would wrap and silently mis-rank.

    Cost: the digit-0 histogram comes FREE from the stats pass (digit 0
    has no prefix), and later digits share one ring pass per digit
    across the AP and AN sides — so RELATIVE mining costs NUM_DIGITS-1
    extra ring passes total whether one or both sides are relative.
    """
    pos_thr, neg_thr = absolute_thresholds(
        stats["min_within"], stats["max_between"], cfg
    )
    ap_rel = cfg.ap_mining_method in _RELATIVE
    an_rel = cfg.an_mining_method in _RELATIVE
    if not (ap_rel or an_rel):
        return pos_thr, neg_thr

    # Sparse-positive fast path (see ops.pallas_npair._thresholds): when
    # AP is the only relative side and every query's positive count fits
    # the stats pass's K-slot buffer, the per-rank threshold is an
    # N x K sort — zero extra ring passes.  The cond predicate must be
    # IDENTICAL on every shard (the radix branch runs ppermute
    # collectives; shards disagreeing on the branch would deadlock), so
    # the overflow check is pmax-reduced over the mesh axis.
    if ap_rel and not an_rel and "topk_same" in stats:
        def radix(include_ap):
            return _ring_radix_thresholds(
                feats, labels, my_rank, axis_name, cfg, stats, cache,
                pos_thr, neg_thr, include_ap=include_ap,
                include_an=an_rel)

        kcap = stats["topk_same"].shape[1]
        # comm marker (obs.fleet.comms): pmax lowers to a (scalar)
        # all-reduce — unscoped, its bytes would be silently absorbed
        # by the grad-sync allreduce CLAIM in the fleet reconciliation
        # instead of being marker-attributed.
        with jax.named_scope("comm/allreduce"):
            fits = jax.lax.pmax(
                stats["count_same"].max(), axis_name) <= kcap

        def fast(_):
            n_local = feats.shape[0]
            g = axis_size(axis_name)
            p = topk_relative_threshold(
                stats["topk_same"], stats["count_same"], cfg.identsn,
                cfg.ap_mining_region,
                count_dtype=population_count_dtype(n_local * n_local * g))
            return p, radix(False)[1]

        return jax.lax.cond(fits, fast, lambda _: radix(True), 0)

    return _ring_radix_thresholds(
        feats, labels, my_rank, axis_name, cfg, stats, cache,
        pos_thr, neg_thr, include_ap=ap_rel, include_an=an_rel)


def _ring_radix_thresholds(
    feats, labels, my_rank, axis_name: str, cfg: NPairLossConfig, stats,
    cache, pos_thr, neg_thr, include_ap, include_an,
):
    """The streamed radix-selection path of ``_ring_thresholds`` (see
    there), restricted to the requested sides."""
    sides = {}
    if include_ap:
        sides["ap"] = (True, cfg.identsn, cfg.ap_mining_region,
                       stats["count_same"], stats["hist0_same"])
    if include_an:
        sides["an"] = (False, cfg.diffsn, cfg.an_mining_region,
                       stats["count_diff"], stats["hist0_diff"])
    if not sides:
        return pos_thr, neg_thr

    n_local = feats.shape[0]
    g = axis_size(axis_name)

    def prep_hist(side, hist):
        """Global-region sides rank over the whole block: sum the
        per-query histograms (in the overflow-safe dtype) and share."""
        _, _, region, _, _ = sides[side]
        if region == MiningRegion.GLOBAL:
            cdt = population_count_dtype(n_local * n_local * g)
            hist = jnp.broadcast_to(
                hist.sum(axis=0, keepdims=True, dtype=cdt),
                (n_local, RADIX_BINS),
            )
        return hist

    states, empties = {}, {}
    for s, (use_same, sn, region, counts, hist0) in sides.items():
        if region == MiningRegion.GLOBAL:
            cdt = population_count_dtype(n_local * n_local * g)
            total = counts.astype(cdt).sum()
            k = jnp.broadcast_to(_relative_pos(total[None], sn)[0], (n_local,))
            empties[s] = jnp.broadcast_to(total == 0, (n_local,))
        else:
            k = _relative_pos(counts, sn)
            empties[s] = counts == 0
        states[s] = radix_update(radix_begin(k), prep_hist(s, hist0))

    for digit in range(1, NUM_DIGITS):
        hists = _multi_digit_hist_pass(
            feats, labels, my_rank, axis_name,
            {s: (sides[s][0], states[s][1]) for s in sides}, digit,
            cache=cache,
        )
        for s in sides:
            states[s] = radix_update(states[s], prep_hist(s, hists[s]))

    vals = {
        s: _clamp_negative(radix_finish(states[s], empties[s]))
        for s in sides
    }
    return vals.get("ap", pos_thr), vals.get("an", neg_thr)


# ---------------------------------------------------------------------------
# Pass 2: selection + stabilized exp sums (+ counts)
# ---------------------------------------------------------------------------


def _loss_pass(
    feats, labels, my_rank, pos_thr, neg_thr, max_all, cfg, axis_name: str,
    cache=None,
):
    n_local = feats.shape[0]
    carry = {
        "ident_sum": jnp.zeros((n_local,), jnp.float32),
        "diff_sum": jnp.zeros((n_local,), jnp.float32),
        "ident_num": jnp.zeros((n_local,), jnp.float32),
        "diff_num": jnp.zeros((n_local,), jnp.float32),
    }

    def accum(c, sims, blk_labels, blk_rank):
        same, diff = _block_masks(labels, blk_labels, my_rank, blk_rank, n_local)
        sel = selection_mask(sims, same, diff, pos_thr, neg_thr, cfg)
        sel_pos = same & sel
        sel_neg = diff & sel
        sim_exp = jnp.exp(sims - max_all[:, None])
        c = dict(c)
        c["ident_sum"] = c["ident_sum"] + jnp.where(sel_pos, sim_exp, 0.0).sum(1)
        c["diff_sum"] = c["diff_sum"] + jnp.where(sel_neg, sim_exp, 0.0).sum(1)
        c["ident_num"] = c["ident_num"] + sel_pos.sum(1).astype(jnp.float32)
        c["diff_num"] = c["diff_num"] + sel_neg.sum(1).astype(jnp.float32)
        return c

    if cache is not None:
        return _cache_scan(cache, accum, carry, axis_name)

    rotating = {"f": feats, "l": labels, "rank": my_rank}

    def body(c, rot, step):
        return accum(c, _tile(feats, rot["f"]), rot["l"], rot["rank"]), rot

    carry, _ = _ring_scan(axis_name, body, carry, rotating)
    return carry


# ---------------------------------------------------------------------------
# Pass 3 (backward): ring allreduce of database-role grads
# ---------------------------------------------------------------------------


def _backward_pass(
    feats,
    labels,
    my_rank,
    pos_thr,
    neg_thr,
    max_all,
    ident_sum,
    all_sum,
    cfg,
    axis_name: str,
    g_loss,
    grad_mode: str,
    cache=None,
):
    n_local, dim = feats.shape
    num_shards = axis_size(axis_name)

    def weight_tile(sims, same, diff):
        sel = selection_mask(sims, same, diff, pos_thr, neg_thr, cfg)
        sim_exp = jnp.exp(sims - max_all[:, None])
        exp_pos = jnp.where(same & sel, sim_exp, 0.0)
        exp_neg = jnp.where(diff & sel, sim_exp, 0.0)

        def safe(num, den):
            ok = den != 0
            return jnp.where(
                ok[:, None], num / jnp.where(ok, den, 1.0)[:, None], 0.0
            )

        p1 = safe(exp_pos, ident_sum)
        p2 = safe(exp_pos, all_sum)
        p3 = safe(exp_neg, all_sum)
        w = (-p1 + p2 + p3) * (g_loss / jnp.float32(n_local))
        if grad_mode != "reference":
            # "true" autodiff of the guarded log (cu:162-169 semantics)
            # gives exactly 0 for zero-loss queries; the reference path
            # keeps p3 alive for identNum==0 queries (cu:133-146).
            valid = (ident_sum != 0) & (all_sum != 0)
            w = jnp.where(valid[:, None], w, 0.0)
        return w

    carry = {"grad_query": jnp.zeros((n_local, dim), jnp.float32)}
    rotating = {
        "f": feats,
        "l": labels,
        "rank": my_rank,
        # The database-role grad for the block travels WITH the block;
        # after G hops it returns to the owner holding the full sum —
        # the ring equivalent of MPI_Allreduce(SUM) (cu:467-488).
        "grad_db": jnp.zeros((n_local, dim), jnp.float32),
    }

    rotating["grad_db"] = pvary(rotating["grad_db"], (axis_name,))

    def body(c, rot, step):
        # The block still has to rotate (its feats feed the two gemms and
        # the traveling grad rides with it), but the sim tile can replay
        # from the cache: hop order here matches the stats pass exactly.
        if cache is not None:
            sims = cache["sims_cache"][step]
        else:
            sims = _tile(feats, rot["f"])
        same, diff = _block_masks(labels, rot["l"], my_rank, rot["rank"], n_local)
        w = weight_tile(sims, same, diff)
        c = dict(c)
        c["grad_query"] = c["grad_query"] + jnp.dot(
            w, rot["f"],
            preferred_element_type=jnp.float32,
            precision=active_matmul_precision(),
        )
        rot = dict(rot)
        rot["grad_db"] = rot["grad_db"] + jnp.dot(
            w.T, feats,
            preferred_element_type=jnp.float32,
            precision=active_matmul_precision(),
        )
        return c, rot

    carry, rotating = _ring_scan(axis_name, body, carry, rotating)
    # After G hops every block is back home: rotating["grad_db"] is this
    # shard's database-role grad summed over all shards.
    grad_db = rotating["grad_db"]
    grad_query = carry["grad_query"]
    if grad_mode == "reference":
        # 1/G allreduce scale (cu:474) + 0.5/0.5 role merge (cu:492-497).
        return 0.5 * grad_db / jnp.float32(num_shards) + 0.5 * grad_query
    return grad_query + grad_db


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _ring_core(features, labels, cfg, axis_name, top_ks, sim_cache,
               pos_topk, matmul_precision):
    out, _ = _ring_fwd_impl(
        features, labels, cfg, axis_name, top_ks, sim_cache, pos_topk,
        matmul_precision
    )
    return out


def _ring_fwd_impl(features, labels, cfg, axis_name, top_ks, sim_cache,
                   pos_topk=0, matmul_precision=None):
    with _precision_ctx(matmul_precision):
        return _ring_fwd_traced(
            features, labels, cfg, axis_name, top_ks, sim_cache, pos_topk)


def _ring_fwd_traced(features, labels, cfg, axis_name, top_ks, sim_cache,
                     pos_topk=0):
    features = features.astype(jnp.float32)
    n_local = features.shape[0]
    my_rank = jax.lax.axis_index(axis_name).astype(jnp.int32)

    ap_rel = cfg.ap_mining_method in _RELATIVE
    an_rel = cfg.an_mining_method in _RELATIVE
    top_k_max = max(top_ks) if top_ks else 1
    stats = _stats_pass(
        features, labels, my_rank, axis_name, top_k_max,
        hist0_same=ap_rel,
        hist0_diff=an_rel,
        emit_sims=sim_cache,
        # The K-slot buffer only pays when AP is the sole relative side
        # (see _ring_thresholds).
        topk_same_k=pos_topk if ap_rel and not an_rel else 0,
    )
    cache = None
    if sim_cache:
        cache = {k: stats[k]
                 for k in ("sims_cache", "labels_cache", "rank_cache")}
    pos_thr, neg_thr = _ring_thresholds(
        features, labels, my_rank, axis_name, cfg, stats, cache=cache
    )
    sums = _loss_pass(
        features, labels, my_rank, pos_thr, neg_thr, stats["max_all"],
        cfg, axis_name, cache=cache,
    )
    ident_sum = sums["ident_sum"]
    all_sum = ident_sum + sums["diff_sum"]
    valid = (ident_sum != 0) & (all_sum != 0)
    log_q = jnp.where(
        valid, jnp.log(jnp.where(valid, ident_sum / all_sum, 1.0)), 0.0
    )
    loss = -log_q.sum() / jnp.float32(n_local)

    # Recall@k from the streamed top-(k+1) lists.  Threshold = the
    # descending-sorted value at index min(k, size-1) over the exp'd row
    # (cu:190); exp is monotone, so raw-sim comparison is equivalent.
    n_total_minus1 = n_local * axis_size(axis_name) - 1
    metrics: Dict[str, jax.Array] = {}
    for k in top_ks:
        thr_idx = jnp.minimum(k, n_total_minus1 - 1)
        thr = jnp.take_along_axis(
            stats["top_sims"], jnp.full((n_local, 1), thr_idx), axis=1
        )[:, 0]
        hit = jnp.any(
            (stats["top_sims"] > thr[:, None]) & stats["top_same"], axis=1
        )
        metrics[f"retrieve_top{k}"] = (
            hit.sum().astype(jnp.float32) / jnp.float32(n_local)
        )
    metrics["feature_asum"] = (
        jnp.abs(features).sum() / jnp.float32(n_local)
    )
    metrics["ident_num"] = sums["ident_num"].sum()
    metrics["diff_num"] = sums["diff_num"].sum()

    residuals = {
        "features": features,
        "labels": labels,
        "pos_thr": pos_thr,
        "neg_thr": neg_thr,
        "max_all": stats["max_all"],
        "ident_sum": ident_sum,
        "all_sum": all_sum,
        # The cached sim tiles ride the residuals so the backward ring
        # replays instead of recomputing; None when caching is off.
        "cache": cache,
    }
    return (loss, metrics), residuals


def _ring_fwd(features, labels, cfg, axis_name, top_ks, sim_cache,
              pos_topk, matmul_precision):
    return _ring_fwd_impl(
        features, labels, cfg, axis_name, top_ks, sim_cache, pos_topk,
        matmul_precision
    )


def _ring_bwd(cfg, axis_name, top_ks, sim_cache, pos_topk,
              matmul_precision, res, cotangents):
    with _precision_ctx(matmul_precision):
        return _ring_bwd_traced(
            cfg, axis_name, top_ks, sim_cache, pos_topk, res, cotangents)


def _ring_bwd_traced(cfg, axis_name, top_ks, sim_cache, pos_topk, res,
                     cotangents):
    g_loss, _ = cotangents  # metrics are monitors, non-differentiable
    my_rank = jax.lax.axis_index(axis_name).astype(jnp.int32)
    d_features = _backward_pass(
        res["features"],
        res["labels"],
        my_rank,
        res["pos_thr"],
        res["neg_thr"],
        res["max_all"],
        res["ident_sum"],
        res["all_sum"],
        cfg,
        axis_name,
        g_loss,
        cfg.grad_mode,
        cache=res["cache"],
    )
    labels = res["labels"]
    if jnp.issubdtype(labels.dtype, jnp.floating):
        d_labels = jnp.zeros(labels.shape, labels.dtype)
    else:
        d_labels = np.zeros(labels.shape, jax.dtypes.float0)
    return d_features, d_labels


_ring_core.defvjp(_ring_fwd, _ring_bwd)


def ring_npair_loss_and_metrics(
    features: jax.Array,
    labels: jax.Array,
    cfg: NPairLossConfig = NPairLossConfig(),
    axis_name: str = "dp",
    top_ks: Sequence[int] = (1, 5, 10),
    sim_cache: Optional[bool] = None,
    pos_topk: Optional[int] = None,
    matmul_precision: Optional[str] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Blockwise-ring N-pair loss + retrieval metrics for one shard.

    Call under ``shard_map`` over ``axis_name``.  Semantically identical
    to ``npair_loss_with_aux`` + ``retrieval_metrics`` for absolute
    mining methods, but the pool is never gathered: blocks stream over
    the ring, and memory is O(N x N_block) — unless ``sim_cache`` is
    active (the default when the (G, N, N) slice fits, see below).

    Gradient semantics follow ``cfg.grad_mode`` exactly like the dense
    path ("reference": 0.5/0.5 role merge with the 1/G allreduce scale).

    ``sim_cache``: keep this shard's (G, N, N) stack of sim tiles from
    the stats pass and replay it in the later passes — the radix-digit
    and loss passes then run locally with no ppermute and no fp32
    matmul recompute, and the backward ring reuses the tiles.
    Bit-identical to recompute.  Default ``None`` auto-enables when the
    slice is at most ``SIM_CACHE_AUTO_BYTES``; ``False`` restores pure
    O(N x N_block) streaming memory.

    ``pos_topk``: K-slot sparse-positive fast path for RELATIVE_* AP
    mining (see ``_ring_thresholds``): the stats pass keeps each
    query's K largest same-label sims, and when every positive count
    fits the buffer the AP threshold costs zero extra ring passes — the
    flagship config then streams as few passes as absolute mining.  A
    mesh-uniform ``lax.cond`` falls back to radix selection when a
    label group overflows.  Default ``None`` = auto (8 slots); 0
    disables the buffer.

    ``matmul_precision``: ``None``/``"highest"`` for oracle bit-parity;
    ``"default"`` opts every ring gemm into the ~6x single-pass bf16
    MXU mode (see ``ops.npair_loss.resolve_matmul_precision``).
    """
    _check_cfg(cfg)
    if sim_cache is None:
        g = axis_size(axis_name)
        n = features.shape[0]
        sim_cache = resolve_sim_cache_auto(g * n * n * 4, "ring")
    pos_topk = 8 if pos_topk is None else int(pos_topk)
    if pos_topk < 0:
        raise ValueError(f"pos_topk must be >= 0, got {pos_topk}")
    return _ring_core(
        features, labels, cfg, axis_name, tuple(top_ks), bool(sim_cache),
        pos_topk, matmul_precision
    )
