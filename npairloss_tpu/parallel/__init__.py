"""Distribution: multi-process runtime, device-mesh plumbing +
ring-blockwise negative pooling."""

from npairloss_tpu.parallel._compat import shard_map
from npairloss_tpu.parallel.distributed import (
    initialize_distributed,
    process_local_batch,
    process_topology,
)
from npairloss_tpu.parallel.mesh import (
    DEFAULT_AXIS,
    data_parallel_mesh,
    mesh_topology,
    shard_batch,
    sharded_npair_loss_fn,
)
from npairloss_tpu.parallel.ring import (
    ring_npair_loss_and_metrics,
    ring_supported,
)

__all__ = [
    "DEFAULT_AXIS",
    "data_parallel_mesh",
    "initialize_distributed",
    "mesh_topology",
    "process_local_batch",
    "process_topology",
    "shard_batch",
    "sharded_npair_loss_fn",
    "ring_npair_loss_and_metrics",
    "ring_supported",
    "shard_map",
]
