from npairloss_tpu.parallel.mesh import (
    DEFAULT_AXIS,
    data_parallel_mesh,
    shard_batch,
    sharded_npair_loss_fn,
)
