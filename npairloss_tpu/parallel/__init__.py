"""Distribution: multi-process runtime, device-mesh plumbing +
ring-blockwise negative pooling."""

from npairloss_tpu.parallel._compat import shard_map
from npairloss_tpu.parallel.distributed import (
    initialize_distributed,
    process_local_batch,
    process_topology,
)
from npairloss_tpu.parallel.mesh import (
    DEFAULT_AXIS,
    build_mesh,
    data_parallel_mesh,
    mesh_topology,
    shard_batch,
    sharded_npair_loss_fn,
)
from npairloss_tpu.parallel.partition import (
    PartitionRuleError,
    load_partition_rules,
    match_partition_rules,
    match_partition_shardings,
    model_parallel_rules,
    partition_summary,
    partition_table,
    place_tree,
    render_partition_table,
    replicated_rules,
)
from npairloss_tpu.parallel.plan import (
    EnginePlan,
    plan_engine,
    plan_for_mesh,
    ring_device_order,
)
from npairloss_tpu.parallel.ring import (
    ring_npair_loss_and_metrics,
    ring_supported,
)

__all__ = [
    "DEFAULT_AXIS",
    "EnginePlan",
    "PartitionRuleError",
    "build_mesh",
    "data_parallel_mesh",
    "initialize_distributed",
    "load_partition_rules",
    "match_partition_rules",
    "match_partition_shardings",
    "mesh_topology",
    "model_parallel_rules",
    "partition_summary",
    "partition_table",
    "place_tree",
    "plan_engine",
    "plan_for_mesh",
    "process_local_batch",
    "process_topology",
    "render_partition_table",
    "replicated_rules",
    "ring_device_order",
    "shard_batch",
    "sharded_npair_loss_fn",
    "ring_npair_loss_and_metrics",
    "ring_supported",
    "shard_map",
]
