"""Device-mesh plumbing for the global negative pool.

The reference's distribution model is one MPI rank per GPU with
MPI_Allgather'd embeddings (npair_multi_class_loss.cu:17-43) and an
MPI_Allreduce of database-side gradients (cu:462-489) — collectives on CPU
buffers, serialized against compute.  Here the same semantics ride the TPU
interconnect: a 1-D ``jax.sharding.Mesh`` over the data-parallel axis, the
loss body wrapped in ``shard_map`` so ``jax.lax.all_gather``/``psum`` become
ICI (or DCN, multi-slice) collectives fused into the step graph by XLA.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from npairloss_tpu.ops.npair_loss import NPairLossConfig, npair_loss_with_aux
from npairloss_tpu.parallel._compat import shard_map

DEFAULT_AXIS = "dp"


def data_parallel_mesh(
    devices: Optional[Sequence[jax.Device]] = None, axis: str = DEFAULT_AXIS
) -> Mesh:
    """A 1-D mesh over all (or the given) devices, in process-major
    (ring) order: one ``ppermute`` rotation then crosses the DCN once
    per host boundary — the minimum — instead of on arbitrary hops
    (``parallel.plan.ring_device_order``)."""
    from npairloss_tpu.parallel.plan import ring_device_order

    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(ring_device_order(devices)), (axis,))


def build_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    mp: int = 1,
    axis: str = DEFAULT_AXIS,
    mp_axis: str = "mp",
) -> Mesh:
    """The pod mesh: 1-D data-parallel (``mp=1`` — byte-identical to
    :func:`data_parallel_mesh`), or 2-D ``dp x mp`` when a partition
    ruleset shards parameters.

    The ``mp`` axis is the INNER (fastest-varying) one over the
    process-major device order, so model-parallel groups land on
    adjacent chips of one host whenever ``mp`` divides the per-host
    device count — parameter collectives ride ICI, and only the
    data-parallel axis (batch all_gather, grad all-reduce) ever
    crosses the DCN.  That is the TPU-v4 paper's placement rule
    (PAPERS.md): spend the cheap wires on the chatty axis.
    """
    from npairloss_tpu.parallel.plan import ring_device_order

    devices = ring_device_order(
        list(devices) if devices is not None else jax.devices())
    mp = int(mp) if mp else 1
    if mp <= 1:
        return Mesh(np.array(devices), (axis,))
    if len(devices) % mp:
        raise ValueError(
            f"--mp {mp} does not divide the {len(devices)}-device mesh")
    arr = np.array(devices).reshape(len(devices) // mp, mp)
    return Mesh(arr, (axis, mp_axis))


def mesh_topology(mesh: Mesh, axis: str = DEFAULT_AXIS) -> dict:
    """JSON-able description of a mesh for run manifests (the fleet
    observatory's "what topology produced these streams?" record):
    axes/sizes plus the device→process placement, so an offline reader
    can tell which shards were local to which rank without a live
    backend.

    ``process_count`` prefers the multi-controller runtime's own
    ``jax.process_count()`` when one is initialized, then the declared
    fleet stamp (``NPAIRLOSS_FLEET_PROCESS`` — under that harness every
    device *attribute* claims process 0, so inferring the count from
    per-device ``process_index`` attrs under-reports the fleet), and
    only then the per-device attrs."""
    from npairloss_tpu.obs.fleet.stamp import resolved_process

    devices = list(mesh.devices.flatten())
    attr_count = len({getattr(d, "process_index", 0) for d in devices})
    process_index, resolved_count = resolved_process()
    process_count = max(resolved_count, attr_count)
    return {
        "axis": axis,
        "axes": {str(a): int(s)
                 for a, s in zip(mesh.axis_names, mesh.devices.shape)},
        "devices": len(devices),
        "device_ids": [d.id for d in devices],
        "device_process": [getattr(d, "process_index", 0) for d in devices],
        "process_count": process_count,
        "process_index": process_index,
    }


def shard_batch(mesh: Mesh, batch, axis: str = DEFAULT_AXIS):
    """Place a host batch with its leading dim sharded over ``axis``."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), batch)


def sharded_npair_loss_fn(
    mesh: Mesh,
    cfg: NPairLossConfig = NPairLossConfig(),
    axis: str = DEFAULT_AXIS,
) -> Callable:
    """Build ``f(features, labels) -> (loss, aux)`` running under shard_map.

    ``features``/``labels`` are globally-sharded arrays (leading dim split
    over ``axis``); each shard computes the reference's per-rank loss over the
    all-gathered pool.  Outputs gain a leading per-rank axis of size G —
    ``loss`` comes back as shape (G,) (each MPI rank of the reference reports
    its own loss; their mean is the pod-level monitor).
    """

    def per_shard(features, labels):
        loss, aux = npair_loss_with_aux(features, labels, cfg, axis_name=axis)
        stack = lambda x: jnp.asarray(x)[None]
        return stack(loss), jax.tree_util.tree_map(stack, aux)

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(axis)),
    )
