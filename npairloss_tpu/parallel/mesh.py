"""Device-mesh plumbing for the global negative pool.

The reference's distribution model is one MPI rank per GPU with
MPI_Allgather'd embeddings (npair_multi_class_loss.cu:17-43) and an
MPI_Allreduce of database-side gradients (cu:462-489) — collectives on CPU
buffers, serialized against compute.  Here the same semantics ride the TPU
interconnect: a 1-D ``jax.sharding.Mesh`` over the data-parallel axis, the
loss body wrapped in ``shard_map`` so ``jax.lax.all_gather``/``psum`` become
ICI (or DCN, multi-slice) collectives fused into the step graph by XLA.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from npairloss_tpu.ops.npair_loss import NPairLossConfig, npair_loss_with_aux
from npairloss_tpu.parallel._compat import shard_map

DEFAULT_AXIS = "dp"


def data_parallel_mesh(
    devices: Optional[Sequence[jax.Device]] = None, axis: str = DEFAULT_AXIS
) -> Mesh:
    """A 1-D mesh over all (or the given) devices."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis,))


def mesh_topology(mesh: Mesh, axis: str = DEFAULT_AXIS) -> dict:
    """JSON-able description of a mesh for run manifests (the fleet
    observatory's "what topology produced these streams?" record):
    axis/size plus the device→process placement, so an offline reader
    can tell which shards were local to which rank without a live
    backend."""
    devices = list(mesh.devices.flatten())
    return {
        "axis": axis,
        "devices": len(devices),
        "device_ids": [d.id for d in devices],
        "device_process": [getattr(d, "process_index", 0) for d in devices],
        "process_count": len({getattr(d, "process_index", 0)
                              for d in devices}),
    }


def shard_batch(mesh: Mesh, batch, axis: str = DEFAULT_AXIS):
    """Place a host batch with its leading dim sharded over ``axis``."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), batch)


def sharded_npair_loss_fn(
    mesh: Mesh,
    cfg: NPairLossConfig = NPairLossConfig(),
    axis: str = DEFAULT_AXIS,
) -> Callable:
    """Build ``f(features, labels) -> (loss, aux)`` running under shard_map.

    ``features``/``labels`` are globally-sharded arrays (leading dim split
    over ``axis``); each shard computes the reference's per-rank loss over the
    all-gathered pool.  Outputs gain a leading per-rank axis of size G —
    ``loss`` comes back as shape (G,) (each MPI rank of the reference reports
    its own loss; their mean is the pod-level monitor).
    """

    def per_shard(features, labels):
        loss, aux = npair_loss_with_aux(features, labels, cfg, axis_name=axis)
        stack = lambda x: jnp.asarray(x)[None]
        return stack(loss), jax.tree_util.tree_map(stack, aux)

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(axis)),
    )
