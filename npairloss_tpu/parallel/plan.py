"""DCN-aware engine planning — which exchange pattern should this mesh run?

The TPU-v4 embedding-hardware paper's central constraint (PAPERS.md) is
the ICI-vs-DCN bandwidth asymmetry: within a host the chip fabric moves
hundreds of GB/s, across hosts the data-center network moves ~an order
of magnitude less.  The two loss engines exercise that asymmetry very
differently:

  * **dense** issues one fused ``all_gather`` of the whole pod pool
    before the similarity matmul — lowest latency on ICI, but the
    gather GATES the matmul, so on DCN the step eats the full
    cross-host transfer up front;
  * **ring** streams the pool over ``ppermute`` hops, one
    block-matmul per hop — each hop's transfer can hide under the
    previous hop's compute, so a DCN hop that fits under the per-hop
    matmul costs (almost) nothing.

``plan_engine`` makes that choice explicit and auditable: pure integer
arithmetic over the mesh's host topology and the roofline interconnect
peaks (``obs.perf.roofline.interconnect_peak``), returning an
:class:`EnginePlan` whose ``reason`` says why — and the CLI stamps the
plan into the run manifest, so "which engine and why" is provenance,
not a flag someone once passed.

Ring hop ordering rides the same topology: ``ring_device_order`` keeps
devices process-major, so one rotation crosses the DCN exactly
``hosts`` times (one hop per host boundary) instead of up to ``G``
times under an interleaved order.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

# A dense per-shard similarity block bigger than this routes to the
# streaming engine even on a single host (the blockwise/ring engines
# exist exactly for pools whose matrix does not fit).
DENSE_SIM_BUDGET_BYTES = 2 << 30


def ring_device_order(devices: Sequence) -> List:
    """Process-major device order: all of host 0's chips, then host
    1's, ...  A ring over this order crosses the DCN once per host
    boundary — the minimum any ring over P hosts can do — instead of
    on (up to) every hop.  Within a host, id order keeps the layout
    deterministic."""
    return sorted(devices,
                  key=lambda d: (getattr(d, "process_index", 0), d.id))


def host_counts(devices: Sequence) -> Dict[int, int]:
    """Device count per owning process (host), for topology records."""
    counts: Dict[int, int] = {}
    for d in devices:
        p = int(getattr(d, "process_index", 0))
        counts[p] = counts.get(p, 0) + 1
    return counts


@dataclasses.dataclass(frozen=True)
class EnginePlan:
    """One auditable engine decision, manifest-ready via ``to_dict``."""

    engine: str                  # the choice: "dense" | "ring"
    requested: str               # what the caller asked ("auto" or explicit)
    link: str                    # slowest link a collective crosses
    devices: int
    hosts: int
    shard_rows: int              # batch rows per mesh shard
    emb_dim: int
    hop_bytes: float             # one ring hop's payload per device
    gather_bytes: float          # dense all_gather receive per device
    dense_sim_bytes: float       # per-shard similarity block, fp32
    peak_bytes_per_s: float      # interconnect_peak(spec, link)
    peak_known: bool
    t_hop_comm_us: float         # hop transfer at link peak
    t_hop_compute_us: float      # per-hop sim block matmul at chip peak
    comm_hidden: bool            # hop transfer fits under hop compute
    cross_host_hops: int         # DCN crossings per ring rotation
    device_kind: str
    reason: str

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def plan_engine(
    n_devices: int,
    n_hosts: int,
    shard_rows: int,
    emb_dim: int,
    device_kind: str = "",
    requested: str = "auto",
    itemsize: int = 4,
    dense_sim_budget: int = DENSE_SIM_BUDGET_BYTES,
) -> EnginePlan:
    """The engine decision as pure arithmetic (unit-testable without a
    backend):

      * one shard's hop payload is ``shard_rows * emb_dim * itemsize``;
      * a ring hop's transfer time at the slowest link's peak is
        compared against the hop's own sim-block matmul at the chip's
        peak FLOP/s — if the transfer hides under the compute, the
        ring's cross-host cost is ~zero and it wins on DCN;
      * if it does not hide, dense wins (its gather moves fewer
        serialized bytes than G-1 exposed hops);
      * on a single host, dense wins unless its per-shard similarity
        block exceeds ``dense_sim_budget`` (memory, not bandwidth, is
        the binding constraint there);
      * an explicit ``requested`` engine is honored verbatim — the plan
        then just records what the auto choice would have said.
    """
    from npairloss_tpu.obs.perf.roofline import chip_peaks, interconnect_peak

    if n_devices < 1 or n_hosts < 1 or n_hosts > n_devices:
        raise ValueError(
            f"bad topology: {n_devices} devices / {n_hosts} hosts")
    if requested not in ("auto", "dense", "ring", "blockwise"):
        raise ValueError(f"unknown engine {requested!r}")
    spec = chip_peaks(device_kind)
    link = "dcn" if n_hosts > 1 else "ici"
    peak = interconnect_peak(spec, link)
    hop_bytes = float(shard_rows) * emb_dim * itemsize
    gather_bytes = hop_bytes * max(n_devices - 1, 0)
    pool_rows = shard_rows * n_devices
    dense_sim_bytes = float(shard_rows) * pool_rows * 4  # fp32 sim block
    t_hop_comm = hop_bytes / peak if peak else float("inf")
    t_hop_compute = (2.0 * shard_rows * shard_rows * emb_dim) / spec.flops
    comm_hidden = t_hop_comm <= t_hop_compute
    cross_host_hops = n_hosts if n_hosts > 1 else 0

    if n_devices == 1:
        auto, why = "dense", "single shard: nothing to exchange"
    elif dense_sim_bytes > dense_sim_budget:
        # Memory outranks bandwidth on every link: a pod-global pool
        # whose dense similarity block does not fit must stream,
        # whatever the gather would have cost.
        auto, why = "ring", (
            f"the dense per-shard similarity block is "
            f"{dense_sim_bytes / 1e9:.2f} GB (> "
            f"{dense_sim_budget / 1e9:.2f} GB budget) over {link}: "
            "stream it")
    elif n_hosts > 1:
        if comm_hidden:
            auto, why = "ring", (
                f"cross-host ({n_hosts} hosts over {link}): a "
                f"{hop_bytes / 1e6:.2f} MB ppermute hop "
                f"({t_hop_comm * 1e6:.0f} us at {peak / 1e9:.0f} GB/s) "
                f"hides under the {t_hop_compute * 1e6:.0f} us per-hop "
                "sim matmul — streamed hops cost ~nothing")
        else:
            auto, why = "dense", (
                f"cross-host but a {hop_bytes / 1e6:.2f} MB hop "
                f"({t_hop_comm * 1e6:.0f} us at {peak / 1e9:.0f} GB/s) "
                f"does NOT hide under {t_hop_compute * 1e6:.0f} us of "
                f"per-hop compute: {n_devices - 1} exposed hops would "
                "cost more than one fused all_gather")
    else:
        auto, why = "dense", (
            f"single host over {link}: one fused all_gather "
            f"({gather_bytes / 1e6:.2f} MB/device at "
            f"{peak / 1e9:.0f} GB/s) beats {max(n_devices - 1, 0)} "
            "serialized hops")

    if requested != "auto":
        engine = requested
        reason = (f"explicit --engine {requested} "
                  f"(auto would pick {auto}: {why})")
    else:
        engine, reason = auto, why
    return EnginePlan(
        engine=engine, requested=requested, link=link,
        devices=int(n_devices), hosts=int(n_hosts),
        shard_rows=int(shard_rows), emb_dim=int(emb_dim),
        hop_bytes=hop_bytes, gather_bytes=gather_bytes,
        dense_sim_bytes=dense_sim_bytes,
        peak_bytes_per_s=peak, peak_known=spec.known,
        t_hop_comm_us=t_hop_comm * 1e6,
        t_hop_compute_us=t_hop_compute * 1e6,
        comm_hidden=comm_hidden, cross_host_hops=cross_host_hops,
        device_kind=device_kind or spec.device_kind, reason=reason,
    )


def plan_for_mesh(
    mesh,
    global_batch: int,
    emb_dim: int,
    requested: str = "auto",
    process_count: Optional[int] = None,
) -> EnginePlan:
    """``plan_engine`` over a live mesh: host count from the devices'
    owning processes (overridable by ``process_count`` for the
    declared-rank harness, where every device claims process 0 but the
    fleet really spans N controllers), shard rows from the global batch
    over the data-parallel axis."""
    devices = list(mesh.devices.flatten())
    hosts = len(host_counts(devices))
    if process_count is not None and process_count > hosts:
        # A declared fleet cannot spread a mesh thinner than one device
        # per host: a harness process holding a 1-device local mesh
        # plans THAT mesh (no cross-device exchange), however many
        # controllers the fleet declares.
        hosts = min(int(process_count), len(devices))
    dp = int(mesh.devices.shape[0])
    shard_rows = max(int(global_batch) // max(dp, 1), 1)
    kind = getattr(devices[0], "device_kind", "") if devices else ""
    return plan_engine(
        n_devices=len(devices), n_hosts=hosts, shard_rows=shard_rows,
        emb_dim=emb_dim, device_kind=kind, requested=requested,
    )
