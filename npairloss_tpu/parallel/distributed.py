"""Multi-process (multi-host) runtime — the MPI_COMM_WORLD replacement.

The reference runs one MPI process per GPU; every collective spans
``MPI_COMM_WORLD`` (reference: npair_multi_class_loss.cu:32, cu:467),
launched as ``mpirun -np G caffe train ...``.  The TPU-native equivalent
is JAX's multi-controller runtime: every host process calls
``jax.distributed.initialize`` against a shared coordinator, after which
``jax.devices()`` spans ALL processes and a single 1-D mesh over it makes
the in-graph ``all_gather``/``psum`` collectives ride ICI within a host
and DCN across hosts — no code change in the loss or solver.

Launch recipe (the mpirun counterpart):

    # process 0 .. N-1, each on its own host (or simulated on one):
    python -m npairloss_tpu train --solver ... \
        --coordinator HOST:PORT --num-processes N --process-id I

On Cloud TPU pods the three flags can be omitted: ``initialize()``
autodetects from the TPU metadata environment.
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

log = logging.getLogger("npairloss_tpu.distributed")


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-process runtime (idempotent for single-process).

    Must run before the first JAX backend query in the process — JAX
    binds local devices at initialization, exactly as MPI_Init must
    precede any communicator use.  With all arguments ``None`` on a
    non-TPU-pod host this is a no-op (single-process run).
    """
    import jax

    if coordinator_address is None and num_processes is None:
        return  # single-process / TPU-pod autodetect not requested
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def process_topology() -> dict:
    """This process's fleet identity as jax sees it:
    ``{process_index, process_count, local_device_ids}`` — the
    jax-backed source ``obs.fleet.fleet_stamp`` resolves when no
    harness override is declared."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_ids": [d.id for d in jax.local_devices()],
    }


def process_local_batch(mesh, batch, axis: str = "dp"):
    """Assemble a global sharded array from THIS process's batch shard.

    The reference's data model is per-rank loading: each MPI rank's
    MultibatchData produces its own N-row batch, and the gathered pool is
    their concatenation in rank order (cu:17-43).  Multi-controller JAX
    mirrors that: each process passes its local rows; the result is a
    global array whose shard on process p is p's data, concatenated in
    process order along the batch axis.  Single-process meshes fall back
    to a plain device_put.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis))
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(np.asarray(x), sharding), batch
        )
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)
        ),
        batch,
    )
