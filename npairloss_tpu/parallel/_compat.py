"""Version-compat shims for jax APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map.shard_map``
to ``jax.shard_map``: new jax releases only ship the top-level name,
older ones only the experimental module.  Every call site in this repo
(and its tests/benches) imports the resolved symbol from here so the
codebase runs on both sides of the move.

All call sites must pass ``mesh=``/``in_specs=``/``out_specs=`` as
keywords — the positional signatures differ across versions, the
keyword ones do not.

``axis_size`` is the same story one level down: new jax ships
``jax.lax.axis_size(name)``; older releases spell the static size
lookup ``jax.core.axis_frame(name)`` (which returns the int directly).
This module imports only jax, so anything in the repo may import it
without cycles.
"""

from __future__ import annotations

import jax


def _resolve_shard_map():
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map as experimental

    return experimental


def _resolve_axis_size():
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn

    def axis_size(axis_name):
        return jax.core.axis_frame(axis_name)

    return axis_size


def _resolve_pvary():
    # Replicated->varying cast for shard_map's manual-axes rep tracking:
    # current jax spells it jax.lax.pvary, one era spelled it
    # jax.lax.pcast(..., to="varying"), and releases before the varying
    # type system (<= 0.4.x) need no cast at all — identity.
    fn = getattr(jax.lax, "pvary", None)
    if fn is not None:
        return fn
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return lambda x, axis_names: pcast(x, axis_names, to="varying")
    return lambda x, axis_names: x


def lowered_text(lowered) -> str:
    """StableHLO text WITH debug info (source locations / named scopes)
    for a ``jax.stage.Lowered``.

    Newer jax spells this ``lowered.as_text(debug_info=True)``; older
    releases (<= 0.4.x) have no such kwarg — there the MLIR module's own
    printer provides the same payload via
    ``compiler_ir().operation.get_asm(enable_debug_info=True)``.  Plain
    ``as_text()`` strips locations on BOTH sides of the move, so
    anything asserting on ``jax.named_scope`` annotations must come
    through here.
    """
    try:
        return lowered.as_text(debug_info=True)
    except TypeError:
        return lowered.compiler_ir(dialect="stablehlo").operation.get_asm(
            enable_debug_info=True
        )


def _resolve_rep_check_off():
    # shard_map's replication checker has no rule for pallas_call, so a
    # shard-local Pallas kernel (ops/pallas_ivf.py) must switch it off.
    # The kwarg moved with the type system: ``check_rep`` up to the
    # 0.4.x/0.5.x era, ``check_vma`` after the varying-manual-axes
    # rework.  Resolve the spelling once from the signature.
    import inspect

    try:
        params = inspect.signature(_resolve_shard_map()).parameters
    except (TypeError, ValueError):  # pragma: no cover - exotic builds
        return {"check_rep": False}
    for name in ("check_rep", "check_vma"):
        if name in params:
            return {name: False}
    return {}  # pragma: no cover - checker removed entirely


shard_map = _resolve_shard_map()
axis_size = _resolve_axis_size()
pvary = _resolve_pvary()
# Splat into a shard_map call to disable its replication check (needed
# around pallas_call bodies): ``shard_map(f, ..., **REP_CHECK_OFF)``.
REP_CHECK_OFF = _resolve_rep_check_off()

__all__ = ["REP_CHECK_OFF", "axis_size", "lowered_text", "pvary",
           "shard_map"]
