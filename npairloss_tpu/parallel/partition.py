"""Declarative partition rules — the sharding table for pod-scale state.

The reference's distribution story is implicit: one MPI rank per GPU,
parameters replicated, activations split by rank (cu:17-43).  That was
also this repo's story until now — every placement a hand-written
``NamedSharding(mesh, P())``/``P(axis)`` scattered through the solver
and the serving index.  At pod scale that stops being tenable: a bigger
trunk or a bigger pooled batch needs *some* leaves sharded over a
second mesh axis, and hand-placing them per call site is exactly how
the PR 7 ViT root-path bug happened (a rule that silently matched
nothing).

This module is the one home for placement decisions, in the
``match_partition_rules`` idiom (SNIPPETS.md [3]): an ORDERED list of
``(regex, PartitionSpec)`` rules matched against the flattened pytree
path of every leaf.

  * **first match wins** — order expresses priority, so specific rules
    go first and a broad fallback goes last;
  * **scalars are never partitioned** — 0-d / single-element leaves
    resolve to ``P()`` before any rule is consulted (there is nothing
    to split);
  * **unmatched leaves are LOUD** — a leaf no rule matches raises
    :class:`PartitionRuleError` naming the leaf path.  Replication is a
    *decision*, spelled as the explicit fallback rule ``(".*", P())``,
    never a silent default;
  * **no-op rules are visible** — :func:`partition_table` counts the
    leaves each rule matched, so a rule with ``matches == 0`` (the
    silent-no-op shape) shows up in ``train --dump-partitions`` before
    a multi-hour run, not after it.

Leaf paths are ``"/"``-joined (``params/conv1/Conv_0/kernel``,
``opt/momentum_buf/conv1/Conv_0/kernel``), so one rule written against
the param name covers its optimizer twin via ``kernel$``-style anchors
— or excludes it via an explicit ``^params/`` prefix.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class PartitionRuleError(ValueError):
    """A leaf no rule matches, an invalid rule regex/spec, or a spec
    naming an axis the mesh does not have."""


# The shipped default: every leaf replicated — byte-for-byte the
# hand-placed ``NamedSharding(mesh, P())`` behavior this table replaced
# (parity by construction; pinned in tests/test_partition.py).
def replicated_rules():
    from jax.sharding import PartitionSpec as P

    return ((".*", P()),)


class ShardLastDim:
    """Rule-spec sentinel: shard the LAST dim of whatever rank the
    matched leaf has — the output-channel dim of a 2-D Dense kernel
    ``(in, out)`` AND a 4-D conv kernel ``(h, w, in, out)`` alike,
    which no fixed positional PartitionSpec can express for both.
    JSON spelling: ``{"last": "mp"}`` (or a list for a multi-axis
    last dim)."""

    def __init__(self, axes):
        self.axes = tuple(axes) if isinstance(axes, (list, tuple)) \
            else (axes,)

    def spec_for(self, shape):
        from jax.sharding import PartitionSpec as P

        entry = self.axes[0] if len(self.axes) == 1 else self.axes
        return P(*([None] * (max(len(shape), 1) - 1) + [entry]))

    def __repr__(self):
        return f"last_dim{self.axes!r}"

    def __eq__(self, other):
        return isinstance(other, ShardLastDim) and self.axes == other.axes


def model_parallel_rules(mp_axis: str = "mp"):
    """The shipped 2-D starter set: shard the OUTPUT (last) dim of
    weight matrices and conv kernels (and their momentum twins,
    matched by the same ``kernel$`` anchor) over ``mp_axis``;
    everything else — biases, norms, scalars, batch stats —
    replicated.  A cookbook seed, not a law: pass your own table for
    anything finer (docs/DISTRIBUTED.md §Partition-rule cookbook)."""
    return (
        (r"kernel$", ShardLastDim(mp_axis)),
        (".*", None),
    )


def _as_spec(spec):
    """Normalize a rule's spec: a PartitionSpec or :class:`ShardLastDim`
    passes through; a list/tuple of axis entries (None, "axis", or a
    sub-list for multi-axis dims) becomes a PartitionSpec; the dict
    ``{"last": axes}`` becomes a :class:`ShardLastDim` — the
    JSON-config spellings."""
    from jax.sharding import PartitionSpec as P

    if isinstance(spec, (P, ShardLastDim)):
        return spec
    if spec is None:
        return P()
    if isinstance(spec, dict):
        if set(spec) == {"last"}:
            return ShardLastDim(spec["last"])
        raise PartitionRuleError(
            f'dict rule specs must be {{"last": axes}}, got {spec!r}')
    if isinstance(spec, (list, tuple)):
        dims = []
        for d in spec:
            if isinstance(d, list):
                dims.append(tuple(d))
            else:
                dims.append(d)
        return P(*dims)
    raise PartitionRuleError(
        f"rule spec must be a PartitionSpec, ShardLastDim, or a list "
        f"of axis entries, got {spec!r}"
    )


def _resolve_spec(spec, shape):
    """A rule's spec made concrete for one leaf (ShardLastDim needs
    the leaf's rank; PartitionSpecs pass through)."""
    return spec.spec_for(shape) if isinstance(spec, ShardLastDim) else spec


def compile_rules(rules) -> List[Tuple[Any, str, Any]]:
    """Validate + compile a ruleset into ``(compiled_regex, pattern,
    spec)`` triples — loud on a bad regex or spec, at table-build time
    rather than deep inside a jit trace."""
    if not rules:
        raise PartitionRuleError("empty partition ruleset (need at least "
                                 'a fallback rule like (".*", P()))')
    out = []
    for i, rule in enumerate(rules):
        try:
            pattern, spec = rule
        except (TypeError, ValueError):
            raise PartitionRuleError(
                f"rule {i} is not a (pattern, spec) pair: {rule!r}")
        try:
            rx = re.compile(pattern)
        except re.error as e:
            raise PartitionRuleError(
                f"rule {i} pattern {pattern!r} is not a valid regex: {e}")
        out.append((rx, pattern, _as_spec(spec)))
    return out


def tree_path_str(path) -> str:
    """One leaf's pytree path as the ``"/"``-joined string the rules
    match: dict keys and namedtuple fields by name, sequence entries by
    index — ``opt/momentum_buf/conv1/Conv_0/kernel``."""
    parts = []
    for p in path:
        name = getattr(p, "key", None)
        if name is None:
            name = getattr(p, "name", None)
        if name is None:
            name = getattr(p, "idx", None)
        parts.append(str(name) if name is not None else str(p))
    return "/".join(parts)


def _is_scalar(leaf) -> bool:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return True  # python scalar leaf
    return len(shape) == 0 or int(np.prod(shape)) == 1


def match_partition_rules(rules, tree):
    """Resolve a pytree to a matching tree of PartitionSpecs.

    Scalar leaves resolve to ``P()``; every other leaf takes the FIRST
    rule whose regex ``search``-matches its path string.  A leaf with
    no matching rule raises :class:`PartitionRuleError` — replication
    must be an explicit fallback rule, never an accident.
    """
    import jax

    compiled = compile_rules(rules)

    def pick(path, leaf):
        from jax.sharding import PartitionSpec as P

        if _is_scalar(leaf):
            return P()
        name = tree_path_str(path)
        for rx, _pat, spec in compiled:
            if rx.search(name):
                return _resolve_spec(spec, getattr(leaf, "shape", ()))
        raise PartitionRuleError(
            f"no partition rule matches leaf {name!r} "
            f"(shape {tuple(getattr(leaf, 'shape', ()))}); add a rule or "
            'an explicit replicated fallback (".*", P())'
        )

    return jax.tree_util.tree_map_with_path(pick, tree)


def _check_spec_on_mesh(name: str, shape, spec, mesh) -> None:
    """Loud pre-flight for one leaf: every axis the spec names must
    exist on the mesh, and the dimension it splits must divide by the
    axis size — XLA would eventually refuse both, but hours later and
    without the leaf path."""
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    dims = tuple(spec)
    if len(dims) > len(shape):
        raise PartitionRuleError(
            f"leaf {name!r} (shape {tuple(shape)}) has fewer dims than "
            f"its spec {spec}")
    for d, entry in enumerate(dims):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        split = 1
        for ax in axes:
            if ax not in axis_sizes:
                raise PartitionRuleError(
                    f"leaf {name!r}: spec {spec} names axis {ax!r} but the "
                    f"mesh has axes {tuple(mesh.axis_names)}")
            split *= axis_sizes[ax]
        if shape[d] % split:
            raise PartitionRuleError(
                f"leaf {name!r}: dim {d} of shape {tuple(shape)} does not "
                f"divide by {split} (spec {spec} over mesh "
                f"{dict(axis_sizes)})")


def match_partition_shardings(rules, tree, mesh):
    """Rules -> a matching tree of ``NamedSharding`` on ``mesh``, with
    the axis-name/divisibility pre-flight applied per leaf.  This is
    the tree jit's ``in_shardings``/``device_put`` consume."""
    import jax
    from jax.sharding import NamedSharding

    specs = match_partition_rules(rules, tree)
    flat_specs = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    shardings = []
    for (path, leaf), spec in zip(leaves, flat_specs):
        shape = getattr(leaf, "shape", ())
        _check_spec_on_mesh(tree_path_str(path), shape, spec, mesh)
        shardings.append(NamedSharding(mesh, spec))
    treedef = jax.tree_util.tree_structure(tree)
    return jax.tree_util.tree_unflatten(treedef, shardings)


def place_tree(tree, shardings_tree):
    """Place a host pytree per a matching shardings tree.  Single
    process: a plain ``device_put``.  Multi-controller: every process
    holds the full host value (replicated state, or the deterministic
    global batch) and contributes its addressable shards via
    ``make_array_from_callback`` — ``device_put`` cannot place onto
    devices another process owns."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten(tree)
    sh_flat = jax.tree_util.tree_leaves(shardings_tree)
    if jax.process_count() == 1:
        placed = [jax.device_put(x, s) for x, s in zip(flat, sh_flat)]
    else:
        placed = []
        for x, s in zip(flat, sh_flat):
            host = np.asarray(x)
            placed.append(jax.make_array_from_callback(
                host.shape, s, lambda idx, host=host: host[idx]))
    return jax.tree_util.tree_unflatten(treedef, placed)


# -- the diagnostic table (train --dump-partitions; prof stamp) ------------


def partition_table(rules, tree, mesh=None) -> Dict[str, Any]:
    """The resolved rule -> PartitionSpec table over a (possibly
    abstract) pytree: one row per leaf plus per-rule match counts.

    Unlike :func:`match_partition_rules` this never raises on an
    unmatched leaf — it REPORTS it (``unmatched`` list + per-row
    ``rule: None``), because the table is the tool you reach for when
    the ruleset is wrong.  Rules with ``matches == 0`` are the silent
    no-ops ``--dump-partitions`` exists to expose.
    """
    import jax

    compiled = compile_rules(rules)
    counts = [0] * len(compiled)
    rows: List[Dict[str, Any]] = []
    unmatched: List[str] = []
    sharded = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = tree_path_str(path)
        shape = tuple(getattr(leaf, "shape", ()))
        if _is_scalar(leaf):
            rows.append({"path": name, "shape": shape, "rule": None,
                         "spec": "P()", "scalar": True})
            continue
        for i, (rx, pat, spec) in enumerate(compiled):
            if rx.search(name):
                counts[i] += 1
                concrete = _resolve_spec(spec, shape)
                if any(d is not None for d in tuple(concrete)):
                    sharded += 1
                rows.append({"path": name, "shape": shape, "rule": pat,
                             "spec": str(concrete), "scalar": False})
                break
        else:
            unmatched.append(name)
            rows.append({"path": name, "shape": shape, "rule": None,
                         "spec": None, "scalar": False})
    table = {
        "rows": rows,
        "rules": [
            {"pattern": pat, "spec": str(spec), "matches": counts[i]}
            for i, (_rx, pat, spec) in enumerate(compiled)
        ],
        "unmatched": unmatched,
        "leaves": len(rows),
        "sharded_leaves": sharded,
    }
    if mesh is not None:
        table["mesh"] = {
            "axes": {str(a): int(s)
                     for a, s in zip(mesh.axis_names, mesh.devices.shape)},
            "devices": int(mesh.size),
        }
    return table


def partition_summary(rules, tree, mesh=None) -> Dict[str, Any]:
    """The manifest-sized digest of :func:`partition_table`: rules with
    match counts (zero-match rules flagged), leaf totals, unmatched
    count — enough for a post-hoc reader to see whether a rule
    silently no-op'd, without a row per leaf."""
    t = partition_table(rules, tree, mesh=mesh)
    return {
        "rules": t["rules"],
        "leaves": t["leaves"],
        "sharded_leaves": t["sharded_leaves"],
        "unmatched": len(t["unmatched"]),
        "noop_rules": [r["pattern"] for r in t["rules"]
                       if r["matches"] == 0],
        **({"mesh": t["mesh"]} if "mesh" in t else {}),
    }


def render_partition_table(table: Dict[str, Any]) -> str:
    """Human-readable table for ``train --dump-partitions``."""
    lines = ["partition rules (first match wins):"]
    for r in table["rules"]:
        flag = "  <-- matches NOTHING (no-op rule?)" if r["matches"] == 0 \
            else ""
        lines.append(f"  {r['pattern']!r:40s} -> {r['spec']:20s} "
                     f"[{r['matches']} leaves]{flag}")
    if "mesh" in table:
        lines.append(f"mesh: {table['mesh']['axes']} "
                     f"({table['mesh']['devices']} devices)")
    lines.append(f"{table['leaves']} leaves "
                 f"({table['sharded_leaves']} sharded):")
    width = max((len(r["path"]) for r in table["rows"]), default=0)
    for r in table["rows"]:
        spec = r["spec"] if r["spec"] is not None else "UNMATCHED"
        why = "scalar" if r["scalar"] else (r["rule"] or "-")
        lines.append(f"  {r['path']:{width}s}  {str(r['shape']):16s} "
                     f"{spec:20s} via {why}")
    if table["unmatched"]:
        lines.append(f"UNMATCHED leaves ({len(table['unmatched'])}): "
                     + ", ".join(table["unmatched"]))
    return "\n".join(lines)


def load_partition_rules(path: str):
    """Load a ruleset from JSON: ``{"rules": [[pattern, spec], ...]}``
    (or a bare list), where ``spec`` is a list of axis entries — null
    for an unsharded dim, an axis name, or a list of names for a
    multi-axis dim.  ``[]``/null mean replicated.  Compiled (and so
    validated) before returning."""
    with open(path) as f:
        obj = json.load(f)
    rules = obj.get("rules") if isinstance(obj, dict) else obj
    if not isinstance(rules, list):
        raise PartitionRuleError(
            f"{path}: expected a JSON list of [pattern, spec] pairs "
            '(or {"rules": [...]})')
    out = tuple((pat, _as_spec(spec)) for pat, spec in
                (tuple(r) for r in rules))
    compile_rules(out)
    return out


# -- shipped rule tables for the serving gallery ---------------------------

def gallery_rules(axis: str):
    """The serving index's placement, declared: gallery rows (and the
    IVF packed slabs, whose leading dim is clusters) shard over the
    mesh axis; centroid tables replicate; anything new must match or
    fail loudly (no silent replication of a 10^8-row array)."""
    from jax.sharding import PartitionSpec as P

    return (
        (r"^(emb|labels|valid|packed|rows)$", P(axis)),
        (r"^(centroids|cluster_valid)$", P()),
    )
