from npairloss_tpu.cli import main

raise SystemExit(main())
