from npairloss_tpu.utils.profiling import StepTimer, annotate, trace
from npairloss_tpu.utils.debug import (
    assert_all_finite,
    checked,
    debug_checks_enabled,
    enable_debug_checks,
)

__all__ = [
    "StepTimer",
    "annotate",
    "trace",
    "assert_all_finite",
    "checked",
    "debug_checks_enabled",
    "enable_debug_checks",
]
