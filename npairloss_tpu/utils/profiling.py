"""Tracing and profiling (SURVEY.md §5.1).

The reference has no profiling — only commented-out LOG(INFO) wall-clock
probes around its MPI calls and kernels (reference:
npair_multi_class_loss.cu:423, cu:464-468, cu:199).  Here the stages of
the loss graph carry ``jax.named_scope`` annotations (visible in
XProf/Perfetto and in HLO op names), ``trace`` captures a device profile
for TensorBoard/XProf, and ``StepTimer`` gives the wall-clock
steps/sec / embeddings/sec counters the reference never had.
"""

from __future__ import annotations

import collections
import contextlib
import time
from typing import Dict, Optional

import jax

# Stage annotation: ``with annotate("npair/sim"): ...`` names the ops
# traced inside it, so XProf timelines and HLO dumps show the pipeline
# stages (gather / sim / mine / select / loss) instead of a fused soup.
annotate = jax.named_scope


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_trace: bool = False):
    """Capture a device+host profile under ``logdir`` (XProf/TensorBoard
    format; optionally a Perfetto trace too).  Wrap a handful of
    training steps, not the whole run.

    WARNING: do NOT use on tunneled/remote-plugin backends (e.g. a
    relay-attached TPU): the trace RPC can wedge the tunnel for hours.
    Use differential ablation timing there instead
    (``scripts/profile_flagship.py``)."""
    jax.profiler.start_trace(
        logdir, create_perfetto_trace=create_perfetto_trace
    )
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Sliding-window wall-clock throughput meter.

    ``tick(items)`` marks a step boundary and returns the current window
    stats; call with the per-step item count (e.g. batch size) to get
    items/sec (embeddings/sec for this framework's benchmarks).  The
    first tick only arms the timer.  Remember JAX dispatch is async —
    call ``jax.block_until_ready`` on a step output before the final
    tick, or wrap ticks around blocking points.
    """

    def __init__(self, window: int = 50):
        self._durations: collections.deque = collections.deque(maxlen=window)
        self._items: collections.deque = collections.deque(maxlen=window)
        self._last: Optional[float] = None

    def tick(self, items: int = 0) -> Dict[str, float]:
        now = time.perf_counter()
        if self._last is not None:
            self._durations.append(now - self._last)
            self._items.append(items)
        self._last = now
        return self.stats()

    def stats(self) -> Dict[str, float]:
        if not self._durations:
            return {"steps_per_sec": 0.0, "items_per_sec": 0.0,
                    "mean_step_ms": 0.0}
        total = sum(self._durations)
        return {
            "steps_per_sec": len(self._durations) / total,
            "items_per_sec": sum(self._items) / total,
            "mean_step_ms": 1000.0 * total / len(self._durations),
        }

    def reset(self):
        self._durations.clear()
        self._items.clear()
        self._last = None
