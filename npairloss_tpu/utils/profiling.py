"""Tracing and profiling (SURVEY.md §5.1).

The reference has no profiling — only commented-out LOG(INFO) wall-clock
probes around its MPI calls and kernels (reference:
npair_multi_class_loss.cu:423, cu:464-468, cu:199).  Here the stages of
the loss graph carry ``jax.named_scope`` annotations (visible in
XProf/Perfetto and in HLO op names), ``trace`` captures a device profile
for TensorBoard/XProf, and ``StepTimer`` gives the wall-clock
steps/sec / embeddings/sec counters the reference never had.

This module is the DEVICE-side half of the observability story; the
HOST-side half (span tracing of data/dispatch/eval/snapshot/compile,
structured metric sinks, health signals) lives in ``npairloss_tpu.obs``
— see docs/OBSERVABILITY.md for when to reach for which.
"""

from __future__ import annotations

import collections
import contextlib
import os
import time
from typing import Callable, Dict, Optional

import jax

# Stage annotation: ``with annotate("npair/sim"): ...`` names the ops
# traced inside it, so XProf timelines and HLO dumps show the pipeline
# stages (gather / sim / mine / select / loss) instead of a fused soup.
annotate = jax.named_scope


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_trace: bool = False):
    """Capture a device+host profile under ``logdir`` (XProf/TensorBoard
    format; optionally a Perfetto trace too).  Wrap a handful of
    training steps, not the whole run.

    WARNING: do NOT use on tunneled/remote-plugin backends (e.g. a
    relay-attached TPU): the trace RPC can wedge the tunnel for hours.
    Use differential ablation timing there instead
    (``scripts/profile_flagship.py``)."""
    jax.profiler.start_trace(
        logdir, create_perfetto_trace=create_perfetto_trace
    )
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# Distinct-dispatch salting: on a memoizing tunnel backend, a dispatch
# that is byte-identical (same executable, same argument values) to an
# earlier one — even from ANOTHER process (the backend is server-side) —
# may be served from cache and report ~zero time.  Every timed dispatch
# in this module therefore draws a fresh integer salt.  Salts come from
# windows of consecutive integers whose start is drawn from os.urandom,
# so concurrent/successive processes (bench.py children, the profile
# orchestrator's per-variant children, resumed runs) almost surely use
# disjoint values — a PID-derived offset cannot promise that (PIDs
# collide mod any table size).  All values stay below 2**24 so they are
# exactly representable in float32 — past that, consecutive integers
# collapse to the same float32 and the salting silently dies.
_SALT_EXACT_LIMIT = 2 ** 24
_SALT_WINDOW = 1024
_salt_state = {"next": 0, "end": 0}


def _next_salt_int() -> int:
    st = _salt_state
    if st["next"] >= st["end"]:
        start = int.from_bytes(os.urandom(3), "big") % (
            _SALT_EXACT_LIMIT - _SALT_WINDOW)
        st["next"], st["end"] = start, start + _SALT_WINDOW
    n = st["next"]
    st["next"] += 1
    return n


def next_timing_salt() -> float:
    """A process-unique salt for folding into a timed computation's
    dispatch arguments: float32-exact, scaled by 2**-20 (exact power of
    two) so a body's typical ``salt * 1e-6`` perturbation stays tiny
    while the dispatch identity stays unique."""
    return float(_next_salt_int()) * 2.0 ** -20


def dispatch_floor(trials: int = 3) -> float:
    """Measured dispatch+fetch latency floor of the current backend, in
    seconds.

    On tunneled backends ``block_until_ready`` can return before device
    compute finishes and identical dispatches may be served from a memo
    cache (docs/DESIGN.md §6), so honest timing must (a) chain DISTINCT
    computations, (b) synchronize by fetching a scalar to the host, and
    (c) subtract this measured round-trip floor.  ~66 ms on the axon
    tunnel, microseconds on a local backend.
    """
    import numpy as np

    import jax.numpy as jnp

    @jax.jit
    def tiny(x):
        return x.sum()

    float(np.asarray(
        tiny(jnp.full((8, 8), float(_next_salt_int())))))  # compile
    ts = []
    for _ in range(max(trials, 1)):
        t0 = time.perf_counter()
        float(np.asarray(tiny(jnp.full((8, 8), float(_next_salt_int())))))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def time_scan(body, init_carry, *, steps: int = 10, floor: float = 0.0,
              warm: int = 2, repeats: int = 2,
              windows_out: list = None) -> float:
    """Wall-clock one computation with the fetch-synced scan discipline;
    returns milliseconds per iteration — the min over ``repeats`` timed
    windows, since tunnel latency jitter only ever inflates a window
    (bench.py's 08:04 UTC 2026-08-01 dense_abs anomaly).  Pass a list
    as ``windows_out`` to receive every window's ms/iter (artifact
    writers record these so an anomalous min stays diagnosable).

    ``body(carry, s) -> carry`` is a ``lax.scan`` body over ``steps``
    iterations; ``s`` is a float32 that differs every iteration — fold
    it into the computation (e.g. perturb an input by ``s * 1e-6``) so
    scan iterations cannot be CSE'd, and accumulate something
    data-dependent into the carry so no iteration can be elided.  Each
    dispatch additionally carries a fresh salt argument (memoizing
    backends key on argument values, so a distinct salt per CALL is what
    defeats the cache; iteration values may overlap across calls
    harmlessly — memoization is per-dispatch, not per-iteration).  The
    scan is jitted once, run ``warm`` times (compile + one-time backend
    setup), then timed on a further distinct dispatch, synchronized by
    fetching one scalar, with ``floor`` (see :func:`dispatch_floor`)
    subtracted.
    """
    if steps < 1:
        raise ValueError(f"time_scan needs steps >= 1, got {steps}")
    if repeats < 1:
        raise ValueError(f"time_scan needs repeats >= 1, got {repeats}")
    import numpy as np

    import jax.numpy as jnp

    @jax.jit
    def many(c0, salt):
        def step(c, s):
            return body(c, s + salt), ()

        c, _ = jax.lax.scan(
            step, c0, jnp.arange(steps, dtype=jnp.float32)
        )
        return c

    def sync(c) -> float:
        leaf = jax.tree_util.tree_leaves(c)[0]
        return float(np.asarray(jnp.ravel(leaf)[0]))

    salts = [next_timing_salt() for _ in range(warm + repeats)]
    for s in salts[:warm]:
        sync(many(init_carry, jnp.float32(s)))
    best = None
    for s in salts[warm:]:
        t0 = time.perf_counter()
        sync(many(init_carry, jnp.float32(s)))
        dt = max(time.perf_counter() - t0 - floor, 1e-9)
        if windows_out is not None:
            windows_out.append(dt * 1e3 / steps)
        best = dt if best is None else min(best, dt)
    return best * 1e3 / steps


# Peak-FLOP table and cost analysis moved to their one home,
# obs.perf.costs (the perf observatory, docs/OBSERVABILITY.md); these
# re-exports keep the historical import path working.  The MFU
# computation itself is obs.perf.costs.mfu_from_timing — call that, do
# not re-derive flops/dt/peak by hand.
from npairloss_tpu.obs.perf.costs import (  # noqa: E402,F401  (re-export)
    PEAK_FLOPS,
    cost_flops,
    mfu_from_timing,
    peak_flops,
)


class StepTimer:
    """Sliding-window wall-clock throughput meter.

    ``tick(items)`` marks a step boundary and returns the current window
    stats; call with the per-step item count (e.g. batch size) to get
    items/sec (embeddings/sec for this framework's benchmarks).  The
    first tick only arms the timer.  Remember JAX dispatch is async —
    call ``jax.block_until_ready`` on a step output before the final
    tick, or wrap ticks around blocking points.

    ``emit`` (optional) receives each tick's stats dict — pass e.g.
    ``lambda s: telemetry.log("throughput", step, s)`` to route the
    counters through the obs metric pipeline instead of scraping logs.
    """

    def __init__(self, window: int = 50,
                 emit: Optional[Callable[[Dict[str, float]], None]] = None):
        self._durations: collections.deque = collections.deque(maxlen=window)
        self._items: collections.deque = collections.deque(maxlen=window)
        self._last: Optional[float] = None
        self._emit = emit

    def tick(self, items: int = 0) -> Dict[str, float]:
        now = time.perf_counter()
        armed = self._last is not None
        if armed:
            self._durations.append(now - self._last)
            self._items.append(items)
        self._last = now
        stats = self.stats()
        if self._emit is not None and armed:
            self._emit(stats)
        return stats

    def stats(self) -> Dict[str, float]:
        if not self._durations:
            return {"steps_per_sec": 0.0, "items_per_sec": 0.0,
                    "mean_step_ms": 0.0}
        total = sum(self._durations)
        return {
            "steps_per_sec": len(self._durations) / total,
            "items_per_sec": sum(self._items) / total,
            "mean_step_ms": 1000.0 * total / len(self._durations),
        }

    def reset(self):
        self._durations.clear()
        self._items.clear()
        self._last = None
