"""Numerical debug guards (SURVEY.md §5.2).

The reference's correctness hazards — the div/log zero-guards
(reference: npair_multi_class_loss.cu:162-169, cu:412-417) and its
unchecked mixed CPU/GPU blob writes — have no runtime checks at all.
Under jit the purity hazard is gone by construction; what remains worth
guarding is numerics.  This module provides:

  * ``checked(fn)`` — a ``jax.experimental.checkify`` wrapper that
    errors (with location) on any NaN/Inf produced inside ``fn``,
    including division guards, usable under jit;
  * ``assert_all_finite(tree)`` — a host-side assertion for step
    outputs, cheap for scalars/metrics;
  * a process-wide debug flag the Solver consults to validate each
    step's loss/metrics without callers threading a flag through.
"""

from __future__ import annotations

import functools
import os
from typing import Any

import jax
import numpy as np
from jax.experimental import checkify

# Process-wide default from the environment so embedded/driver runs can
# flip the switch without code; the CLI's --debug-checks flag and
# enable_debug_checks() override it either way.
_debug_checks = os.environ.get(
    "NPAIRLOSS_DEBUG_CHECKS", ""
).lower() in ("1", "true", "yes", "on")


def enable_debug_checks(enabled: bool = True) -> None:
    """Process-wide switch: when on, the Solver asserts every step's
    loss/metric scalars are finite (raising with the offending name)."""
    global _debug_checks
    _debug_checks = bool(enabled)


def debug_checks_enabled() -> bool:
    return _debug_checks


def assert_all_finite(tree: Any, name: str = "value") -> None:
    """Host-side: raise FloatingPointError naming the first non-finite
    leaf.  Forces materialization — use on scalars/metrics, not params."""
    leaves = jax.tree_util.tree_leaves_with_path(tree)
    for path, leaf in leaves:
        arr = np.asarray(leaf)
        if arr.dtype.kind in "fc" and not np.isfinite(arr).all():
            raise FloatingPointError(
                f"non-finite {name}{jax.tree_util.keystr(path)}: "
                f"{arr if arr.size <= 8 else 'array with NaN/Inf'}"
            )


def checked(fn, *, div: bool = True, nan: bool = True, oob: bool = False,
            jit: bool = True):
    """Wrap ``fn`` with checkify float/div(/index) error tracking.

    Returns a function with the same signature that raises
    ``checkify.JaxRuntimeError`` on the host when any op inside produced
    NaN/Inf or divided by zero — the runtime teeth for the guards the
    reference hand-rolled at cu:162-169 and cu:412-417.

    The checkified graph is jitted internally (``jit=True``); the error
    throw happens on the host after the compiled call, so do NOT wrap
    the result in another ``jax.jit`` (the error state must surface,
    jit-of-checkify, not checkify-inside-jit).
    """
    errors = frozenset(
        (checkify.float_checks if nan else frozenset())
        | (checkify.div_checks if div else frozenset())
        | (checkify.index_checks if oob else frozenset())
    )
    checked_fn = checkify.checkify(fn, errors=errors)
    if jit:
        checked_fn = jax.jit(checked_fn)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        err, out = checked_fn(*args, **kwargs)
        err.throw()
        return out

    return wrapper
