"""Sync-free stepping — the async execution pipeline (docs/PIPELINE.md).

The reference pays device<->host round-trips between every stage of
every step (npair_multi_class_loss.cu:222-337 runs mining on the host);
the transplant's synchronous loop still blocks on host work each
iteration: batches arrive as NumPy and transfer at dispatch, and any
per-step scalar read (telemetry, the divergence guard) stalls the
dispatch pipeline.  This package removes the steady-state host taxes:

  * :class:`DevicePrefetcher` — a staging thread that ``jax.device_put``s
    loader batches onto the mesh with the step's input sharding ahead of
    need, so the jitted step consumes already-resident, donated buffers;
  * :class:`DispatchController` — a semaphore on in-flight dispatched
    steps, so async dispatch cannot queue unboundedly against a backend
    that wedges under pressure;
  * :class:`MetricWindow` — a device-side metric ring written inside the
    jitted step (plus an in-graph consecutive-non-finite loss counter),
    read back by the host only at display/eval/snapshot window
    boundaries;
  * :func:`enable_compile_cache` — the persistent XLA compilation cache,
    so no process recompiles a program another process already compiled;
  * :class:`HostSyncMonitor` — a counting ``device_put``/``device_get``
    shim that proves (or enforces) the no-mid-window-host-sync contract.

The Solver wires these together behind ``SolverConfig.pipeline``
(CLI ``--pipeline``), default OFF; the pipelined loop is parity-pinned
bit-identical to the synchronous one (tests/test_pipeline.py).
"""

from npairloss_tpu.pipeline.compile_cache import (
    compile_cache_dir,
    disable_compile_cache,
    enable_compile_cache,
)
from npairloss_tpu.pipeline.controller import DispatchController
from npairloss_tpu.pipeline.prefetcher import (
    DevicePrefetcher,
    PrefetchStageError,
)
from npairloss_tpu.pipeline.syncguard import (
    HostSyncMonitor,
    SyncGuardViolation,
    monitor_from_env,
)
from npairloss_tpu.pipeline.window import MetricWindow

__all__ = [
    "DevicePrefetcher",
    "DispatchController",
    "HostSyncMonitor",
    "MetricWindow",
    "PrefetchStageError",
    "SyncGuardViolation",
    "compile_cache_dir",
    "disable_compile_cache",
    "enable_compile_cache",
    "monitor_from_env",
]
