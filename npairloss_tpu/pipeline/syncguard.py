"""Counting ``device_put``/``device_get`` shim — the no-mid-window proof.

``jax.transfer_guard`` does not intercept transfers on the CPU backend
(host-platform arrays are zero-copy), so the CI assertion "the pipelined
loop issues no host transfers between window boundaries" cannot lean on
it.  :class:`HostSyncMonitor` is the counting-shim alternative the
acceptance contract names: it patches the public ``jax.device_put`` /
``jax.device_get`` entry points (the ones every transfer in THIS
codebase's pipelined path goes through — the prefetcher stages with an
explicit ``device_put``, the window read is an explicit ``device_get``)
and records each call with its thread and whether it happened inside an
``allowed()`` region (a window boundary).

Strict mode turns the record into an enforcement: a transfer on the
guarded (train-loop) thread outside an allowed region raises
:class:`SyncGuardViolation`.  The staging thread is exempt by design —
moving the put OFF the step loop's thread is the whole point.

Activation: tests attach a monitor via ``Solver.sync_monitor``; the CI
smoke sets ``NPAIRLOSS_PIPELINE_SYNC_GUARD=strict`` (or ``count``) and
the Solver picks it up via :func:`monitor_from_env`.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Any, Dict, List, Optional

ENV_VAR = "NPAIRLOSS_PIPELINE_SYNC_GUARD"


class SyncGuardViolation(RuntimeError):
    """A host transfer happened mid-window on the guarded thread."""


class HostSyncMonitor:
    """Context manager; patch scope = its ``with`` block.

    The thread that ENTERS the monitor is the guarded one.  Interceptions
    aggregate into integer counters (:meth:`counts`) so a multi-day run
    under ``count`` mode holds O(1) memory; only forbidden calls keep a
    per-event ``{"op", "thread", "guarded_thread", "allowed"}`` record
    (:meth:`violations`) — those are the forensic payload, and there are
    at most a handful before someone notices.
    """

    def __init__(self, strict: bool = False):
        self.strict = strict
        self._counts: Dict[str, int] = {
            "put": 0, "get": 0, "put_guarded": 0, "get_guarded": 0,
        }
        self._violations: List[Dict[str, Any]] = []
        self._local = threading.local()
        self._guard_thread: Optional[int] = None
        self._orig_put = None
        self._orig_get = None
        self._lock = threading.Lock()

    # -- region control (the Solver marks window boundaries) ---------------

    @contextlib.contextmanager
    def allowed(self):
        """Mark a region (window boundary / setup) where host syncs on
        the guarded thread are legitimate."""
        prev = getattr(self._local, "allowed", False)
        self._local.allowed = True
        try:
            yield
        finally:
            self._local.allowed = prev

    # -- interception ------------------------------------------------------

    def _record(self, op: str) -> None:
        thread = threading.get_ident()
        on_guard = thread == self._guard_thread
        allowed = (not on_guard) or getattr(self._local, "allowed", False)
        with self._lock:
            self._counts[op] += 1
            if on_guard:
                self._counts[op + "_guarded"] += 1
            if not allowed:
                self._violations.append({
                    "op": op,
                    "thread": thread,
                    "guarded_thread": on_guard,
                    "allowed": allowed,
                })
        if self.strict and not allowed:
            raise SyncGuardViolation(
                f"mid-window host sync: jax.{op} on the step-loop thread "
                "outside a window boundary (the sync-free contract, "
                "docs/PIPELINE.md)"
            )

    def violations(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._violations)

    def counts(self) -> Dict[str, int]:
        """{"put": n, "get": m, "put_guarded": ..., "get_guarded": ...}"""
        with self._lock:
            return dict(self._counts)

    def __enter__(self) -> "HostSyncMonitor":
        import jax

        self._guard_thread = threading.get_ident()
        orig_put = self._orig_put = jax.device_put
        orig_get = self._orig_get = jax.device_get
        monitor = self

        # Bind the originals into the closures (not monitor._orig_put at
        # call time): __exit__ on the loop thread nulls the attributes
        # while the staging thread may still be inside a wrapper.
        def put(*args, **kwargs):
            monitor._record("put")
            return orig_put(*args, **kwargs)

        def get(*args, **kwargs):
            monitor._record("get")
            return orig_get(*args, **kwargs)

        jax.device_put = put
        jax.device_get = get
        return self

    def __exit__(self, *exc) -> None:
        import jax

        if self._orig_put is not None:
            jax.device_put = self._orig_put
        if self._orig_get is not None:
            jax.device_get = self._orig_get
        self._orig_put = self._orig_get = None


def monitor_from_env() -> Optional[HostSyncMonitor]:
    """Monitor per ``NPAIRLOSS_PIPELINE_SYNC_GUARD``: ``strict`` raises
    on violations, ``count``/``1`` records only, unset/``0`` -> None."""
    mode = os.environ.get(ENV_VAR, "").strip().lower()
    if mode in ("", "0", "off"):
        return None
    return HostSyncMonitor(strict=(mode == "strict"))
