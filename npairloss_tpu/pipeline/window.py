"""Device-side metric ring for the sync-free step loop.

In the synchronous loop every consumer of a step scalar (telemetry, the
loss window, the divergence guard) materializes it on the host — one
sync per step.  :class:`MetricWindow` moves the accumulation into the
jitted step: each step's metric scalars are scattered into a
``[capacity, num_metrics]`` f32 ring riding the step's carry, alongside
an in-graph consecutive-non-finite-loss counter, and the host reads the
whole window back in ONE ``device_get`` at display/eval/snapshot
boundaries (``step/window_sync``).

The metric KEY ORDER is pinned to the jit output dict's own iteration
order (pytree dicts flatten key-sorted), so per-step records
reconstructed by :meth:`read` carry byte-identical key streams to the
synchronous loop's — the parity contract tests/test_pipeline.py pins.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np


class MetricWindow:
    """``keys`` must be the sorted metric names of the step's output
    dict (the order a jitted dict output iterates in); ``capacity`` is
    the max steps between host reads — memory cost is
    ``capacity * len(keys)`` f32, trivial at any real cadence."""

    def __init__(self, keys: Sequence[str], capacity: int):
        if capacity < 1:
            raise ValueError(f"window capacity must be >= 1, got {capacity}")
        if "loss" not in keys:
            raise ValueError("metric keys must include 'loss' (the "
                             "non-finite counter watches it)")
        self.keys = tuple(keys)
        self.capacity = int(capacity)
        self._loss_idx = self.keys.index("loss")

    # -- device side (called inside the jitted step) -----------------------

    def init_ring(self) -> Dict[str, Any]:
        """Fresh ring state (call under jit or let jax stage it)."""
        import jax.numpy as jnp

        return {
            "buf": jnp.zeros((self.capacity, len(self.keys)), jnp.float32),
            "pos": jnp.zeros((), jnp.int32),
            # Consecutive-non-finite-loss streak, carried ACROSS windows
            # (a streak spanning a boundary must not reset), plus the
            # window's max — the guard's cheap trip signal.
            "streak": jnp.zeros((), jnp.int32),
            "max_streak": jnp.zeros((), jnp.int32),
        }

    def update(self, ring: Dict[str, Any],
               metrics: Dict[str, Any]) -> Dict[str, Any]:
        """One step's scalars into the ring; traced into the step."""
        import jax
        import jax.numpy as jnp

        vals = jnp.stack(
            [jnp.asarray(metrics[k]).astype(jnp.float32) for k in self.keys]
        )
        buf = jax.lax.dynamic_update_index_in_dim(
            ring["buf"], vals, ring["pos"], axis=0
        )
        finite = jnp.isfinite(vals[self._loss_idx])
        streak = jnp.where(finite, 0, ring["streak"] + 1).astype(jnp.int32)
        return {
            "buf": buf,
            "pos": ring["pos"] + 1,
            "streak": streak,
            "max_streak": jnp.maximum(ring["max_streak"], streak),
        }

    def reset(self, ring: Dict[str, Any]) -> Dict[str, Any]:
        """Rewind the write position for the next window (device-side —
        jit this with donation so a reset moves no bytes).  The streak
        survives; ``max_streak`` restarts as the streak in flight."""
        import jax.numpy as jnp

        return {
            "buf": jnp.zeros_like(ring["buf"]),
            "pos": jnp.zeros_like(ring["pos"]),
            "streak": ring["streak"],
            "max_streak": jnp.asarray(ring["streak"], jnp.int32),
        }

    # -- host side ---------------------------------------------------------

    def read(self, ring_host: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Per-step metric dicts from a ``device_get`` of the ring, in
        step order, values as ``np.float32`` scalars — key order is
        exactly ``self.keys`` (the sync loop's key stream)."""
        n = int(ring_host["pos"])
        if n > self.capacity:
            raise ValueError(
                f"ring overflowed: {n} writes into capacity "
                f"{self.capacity} — a window boundary was missed"
            )
        buf = np.asarray(ring_host["buf"])[:n]
        return [dict(zip(self.keys, row)) for row in buf]
