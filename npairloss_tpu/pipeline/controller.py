"""Bounded dispatch depth — a semaphore on in-flight jitted steps.

JAX dispatch is asynchronous: without a bound, a sync-free loop can
enqueue thousands of steps against a backend that is stalling, which is
exactly how the tunneled backend wedges under pressure (PROFILE.md,
round 4).  The controller admits at most ``max_in_flight`` dispatched
steps: before dispatching a new one, the loop calls :meth:`reserve`,
which blocks on the OLDEST pending step's completion token until the
bound is respected.  Blocking on a token (``block_until_ready`` on a
tiny per-step output array) synchronizes the host with device progress
WITHOUT transferring anything — it is not a host sync in the
transfer-guard sense.
"""

from __future__ import annotations

import collections


class DispatchController:
    """``reserve()`` before dispatch, ``admit(token)`` after.

    ``token`` is any object with ``block_until_ready()`` — in the Solver
    it is the pipelined step's tiny ``tick`` output (NOT donated into
    the next dispatch, so it stays readable).  ``blocked`` counts how
    often ``reserve`` actually had to wait — a saturated pipeline shows
    ``blocked ~= steps``, an underfed one ~0.
    """

    def __init__(self, max_in_flight: int = 2):
        if max_in_flight < 1:
            raise ValueError(
                f"max_in_flight must be >= 1, got {max_in_flight}"
            )
        self.max_in_flight = max_in_flight
        self._pending: collections.deque = collections.deque()
        self.blocked = 0

    @property
    def in_flight(self) -> int:
        return len(self._pending)

    def reserve(self) -> None:
        """Block until another dispatch is within the bound."""
        while len(self._pending) >= self.max_in_flight:
            oldest = self._pending.popleft()
            oldest.block_until_ready()
            self.blocked += 1

    def admit(self, token) -> None:
        self._pending.append(token)

    def drain(self) -> None:
        """Block until every admitted step has completed."""
        while self._pending:
            self._pending.popleft().block_until_ready()
