"""Persistent XLA compilation cache — compile once per program, ever.

The batch-480 flagship compile ran 25 minutes and wedged the 2026-08-02
tunnel window (PROFILE.md); nothing about that compile was specific to
the process that paid for it.  This module points JAX's persistent
compilation cache at a directory (``--compile-cache DIR`` /
``SolverConfig.compile_cache``), with the thresholds zeroed so every
program is cached — a second process lowering the same step hits the
cache and its ``step/compile`` span collapses from minutes to the
deserialization cost.

The cache is an optimization, never a requirement: any config failure
(older jax without a knob, read-only dir) is logged and ignored.  One
home for the knob-twiddling — ``bench.py`` children, the Solver, and
the CLI all route through :func:`enable_compile_cache`.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger("npairloss_tpu.pipeline")

_ENABLED_DIR: Optional[str] = None


def compile_cache_dir() -> Optional[str]:
    """The directory the cache was enabled at this process, or None."""
    return _ENABLED_DIR


def enable_compile_cache(cache_dir: str) -> Optional[str]:
    """Enable the persistent compilation cache at ``cache_dir``.

    Process-global (jax config) and idempotent; returns the absolute
    path on success, None when the jax build has no cache support.
    Thresholds are zeroed (min compile time / min entry size) because a
    tunneled backend makes even small recompiles expensive.
    """
    global _ENABLED_DIR
    import jax

    path = os.path.abspath(cache_dir)
    if _ENABLED_DIR == path:
        return path
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception as e:  # cache is an optimization, never a requirement
        log.warning("compilation cache unavailable at %s: %s", cache_dir, e)
        return None
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except Exception as e:  # older jax: threshold knob absent
            log.info("compilation cache knob %s unavailable: %s", knob, e)
    try:
        # jax initializes the cache object LAZILY AND ONCE: a process
        # that dispatched anything before this call (the usual case — a
        # Solver construction stages a few constants) latched the cache
        # as "no dir configured, disabled" and would ignore the config
        # update forever.  reset_cache() returns it to pristine so the
        # next compile re-reads the config.  Internal API, so a failure
        # degrades to "cache maybe inactive", never an error.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as e:  # pragma: no cover - jax-internals drift
        log.info("compilation cache re-initialization unavailable: %s", e)
    _ENABLED_DIR = path
    log.info("persistent compilation cache: %s", path)
    return path


def disable_compile_cache() -> None:
    """Turn the persistent cache back off (tests / embedders).

    Sharp edge worth knowing (pinned by tests/test_pipeline.py): an
    executable DESERIALIZED from the cache enforces its input-output
    aliasing exactly as serialized — including donations a fresh compile
    on this backend would have pruned as unusable (CPU).  Code holding
    zero-copy ``np.asarray`` views of donated buffers across steps sees
    them mutate under a cache hit where it happened not to without the
    cache.  The framework never holds such views (checksums and metric
    reads copy immediately); external callers should copy too.
    """
    global _ENABLED_DIR
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", None)
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as e:  # pragma: no cover - jax-internals drift
        log.info("compilation cache disable failed: %s", e)
    _ENABLED_DIR = None
