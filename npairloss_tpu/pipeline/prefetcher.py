"""Device-resident batch prefetch: host batches -> staged device buffers.

The data loader (``data/loader.py``) already overlaps sample+decode with
training on a host thread, but its batches are NumPy — the transfer to
the device happens implicitly at dispatch time, on the training thread,
every step.  :class:`DevicePrefetcher` adds the missing half: a staging
thread that pulls host batches and ``jax.device_put``s them with the
step's input sharding *ahead of need* (depth-k double buffering,
default 2), so the train loop's ``get()`` returns batches that are
already resident and safe to donate into the jitted step.

Failure contract (mirrors the loader's): an exception in the staging
thread — including the ``pipeline.stage`` failpoint — is queued and
re-raised from ``get()`` as :class:`PrefetchStageError` carrying the
batch index; the thread exits and ``close()`` joins it, so SIGTERM /
exception paths drain cleanly (no dangling put against a dying
backend).  ``staged``/``consumed`` count batches through the stage so a
resume can reason about exactly which batch index the pipeline died on.
"""

from __future__ import annotations

import contextlib
import logging
import queue
import threading
from typing import Callable, Iterator, Optional

from npairloss_tpu.resilience import failpoints

log = logging.getLogger("npairloss_tpu.pipeline")


class PrefetchStageError(RuntimeError):
    """The staging thread died; carries the batch index it died on."""

    def __init__(self, batch_index: int, cause: BaseException):
        super().__init__(
            f"pipeline staging failed at batch {batch_index}: "
            f"{type(cause).__name__}: {cause}"
        )
        self.batch_index = batch_index


class _StageFailure:
    __slots__ = ("exc", "batch_index")

    def __init__(self, exc: BaseException, batch_index: int):
        self.exc = exc
        self.batch_index = batch_index


class _EndOfData:
    __slots__ = ()


class DevicePrefetcher:
    """Iterator of device-resident batches, staged ``depth`` ahead.

    Args:
      batches: host iterator yielding (inputs, labels) NumPy batches.
        Only the staging thread touches it (generators are fine).
      place: host batch -> device batch; typically ``Solver._stage_batch``
        (explicit ``jax.device_put`` with the step's input sharding).
      depth: staged batches held ready (>=1).  Device memory cost is
        ``depth`` extra batches — the price of never waiting on a
        transfer.
      span: optional ``(name, **args) -> context`` (Solver._span /
        RunTelemetry.span, both thread-safe) — each staging put is
        recorded as a ``pipeline/stage`` span on the staging thread's
        timeline.
    """

    def __init__(
        self,
        batches: Iterator,
        place: Callable,
        depth: int = 2,
        span: Optional[Callable] = None,
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._it = batches
        self._place = place
        self._span = span
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self.staged = 0  # written by the staging thread only
        self.consumed = 0
        self._thread = threading.Thread(
            target=self._run, name="npairloss-pipeline-stage", daemon=True
        )
        self._thread.start()

    # -- staging thread ----------------------------------------------------

    def _run(self):
        def put(item) -> bool:
            while not self._stop.is_set():
                try:
                    self._queue.put(item, timeout=0.5)
                    return True
                except queue.Full:
                    continue
            return False

        while not self._stop.is_set():
            try:
                try:
                    host = next(self._it)
                except StopIteration:
                    put(_EndOfData())
                    return
                failpoints.fire("pipeline.stage")
                ctx = (self._span("pipeline/stage", batch_index=self.staged)
                       if self._span is not None else contextlib.nullcontext())
                with ctx:
                    dev = self._place(*host)
                self.staged += 1
            except BaseException as exc:  # surfaced in get(), never silent
                put(_StageFailure(exc, self.staged))
                return
            if not put(dev):
                return

    # -- consumer side -----------------------------------------------------

    def get(self):
        """Next device-resident batch; blocks only if staging is behind.

        Raises :class:`PrefetchStageError` when the staging thread died
        (the thread has already exited — ``close()`` just joins), and
        ``StopIteration`` when the host iterator ended.
        """
        if self._stop.is_set():
            raise RuntimeError("prefetcher is closed")
        item = self._queue.get()
        if isinstance(item, _EndOfData):
            self._stop.set()
            raise StopIteration
        if isinstance(item, _StageFailure):
            self._stop.set()
            raise PrefetchStageError(item.batch_index, item.exc) from item.exc
        self.consumed += 1
        return item

    def __iter__(self):
        return self

    def __next__(self):
        return self.get()

    def close(self):
        """Stop staging and join the thread (drains the queue so a put
        blocked on a full queue can observe the stop event)."""
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():  # pragma: no cover - diagnostic only
            log.warning("pipeline staging thread did not join within 5s")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self._stop.set()
        except AttributeError:
            pass
