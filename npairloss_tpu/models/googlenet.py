"""GoogLeNet (Inception v1) embedding backbone in Flax.

The reference net (usage/def.prototxt:1, "GoogleNet") is the standard
Inception-v1 trunk truncated at pool5/7x7_s1 — the 1024-d pooled feature is
the embedding, L2-normalized before the loss (def.prototxt:115-126).  This
is a fresh Flax NHWC implementation designed for the MXU (bf16 activations,
conv+relu fused by XLA), not a translation of the prototxt layer list.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from npairloss_tpu.models.layers import (
    ConvBlock,
    global_avg_pool,
    local_response_norm,
    max_pool,
    space_to_depth,
)
from npairloss_tpu.models.precision import PrecisionPolicy
from npairloss_tpu.ops.normalize import l2_normalize

# Inception block channel plans: (1x1, 3x3red, 3x3, 5x5red, 5x5, pool_proj).
_INCEPTION_PLAN = {
    "3a": (64, 96, 128, 16, 32, 32),
    "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64),
    "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64),
    "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


class Inception(nn.Module):
    plan: Tuple[int, int, int, int, int, int]
    dtype: Any = jnp.float32
    use_bn: bool = False
    # Mixed-precision policy, threaded into every ConvBlock (each block
    # regex-resolves its own path against the policy's rules).
    policy: Optional[PrecisionPolicy] = None
    # Merge the three 1x1 convs that read the block input (b1x1,
    # b3x3_reduce, b5x5_reduce) into ONE conv with p1+p3r+p5r output
    # channels, then slice.  Same dot products, same per-channel
    # ReLU/BN — exact algebra — but the MXU sees one gemm with a full
    # lane tile instead of three thin ones (e.g. 3a: 64/96/16 -> 176;
    # a 16-channel conv occupies 1/8 of the 128-lane systolic axis).
    # Checkpoints interchange via ``fuse_inception_1x1_params``.
    fuse_1x1: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        p1, p3r, p3, p5r, p5, pp = self.plan
        conv = lambda f, k, name: ConvBlock(
            f, k, dtype=self.dtype, use_bn=self.use_bn,
            policy=self.policy, name=name,
        )
        if self.fuse_1x1:
            fused = conv(p1 + p3r + p5r, (1, 1), "fused_1x1")(x, train)
            b1 = fused[..., :p1]
            b3 = fused[..., p1:p1 + p3r]
            b5 = fused[..., p1 + p3r:]
        else:
            b1 = conv(p1, (1, 1), "b1x1")(x, train)
            b3 = conv(p3r, (1, 1), "b3x3_reduce")(x, train)
            b5 = conv(p5r, (1, 1), "b5x5_reduce")(x, train)
        b3 = conv(p3, (3, 3), "b3x3")(b3, train)
        b5 = conv(p5, (5, 5), "b5x5")(b5, train)
        bp = max_pool(x, 3, 1, "SAME")
        bp = conv(pp, (1, 1), "pool_proj")(bp, train)
        return jnp.concatenate([b1, b3, b5, bp], axis=-1)


class GoogLeNetEmbedding(nn.Module):
    """Inception-v1 trunk -> pool5 (1024-d) -> optional L2 normalize.

    Input: NHWC images (224x224x3 canonical).  ``normalize=True`` matches
    the reference's L2Normalize-before-loss topology.
    """

    dtype: Any = jnp.bfloat16
    normalize: bool = True
    use_lrn: bool = True
    # Inception-BN: BatchNorm after every conv (bias dropped), LRN off —
    # the variant that trains from scratch; the BN-free v1 trunk collapses
    # at random init (see ACCURACY.md).  Parameter-parity with the
    # reference's prototxt trunk keeps use_bn=False the default.
    use_bn: bool = False
    # Rematerialize each inception block in the backward pass: trades
    # ~25% more trunk FLOPs for O(stage) activation memory, lifting the
    # batch ceiling / relieving HBM pressure at large per-chip batches
    # (the measured MFU decay from batch 120 -> 480, PROFILE.md).
    # Numerically identical to remat=False.
    remat: bool = False
    # Fused inception 1x1s (see Inception.fuse_1x1): exact algebra,
    # better MXU lane occupancy on the thin reduce branches; weights
    # interchange via fuse_inception_1x1_params.
    fuse_1x1: bool = False
    # Caffe-exact conv1 padding: Caffe pads the 7x7/s2 stem symmetrically
    # (pad: 3, usage/def.prototxt:100) while SAME uses (2, 3) at 224 —
    # same output shape, border-pixel differences only.  Set True when
    # running imported .caffemodel weights for closest-to-source
    # inference (pool layers already agree: SAME's right-biased padding
    # reproduces Caffe's pad-0 ceil pooling at these shapes).
    caffe_pad: bool = False
    # Space-to-depth stem: the 7x7/s2 conv over 3 input channels maps
    # poorly onto the 128-lane MXU (contraction depth 7*7*3 = 147 with
    # C_in=3 on the lane axis).  stem_s2d=True rewrites it as the exact
    # algebraic equivalent: space_to_depth(2) then a 4x4/s1 conv over 12
    # channels (pad (1,2), mirroring SAME's (2,3) on the full grid) —
    # same function, better tiling.  Weights
    # convert losslessly both ways via `conv1_kernel_to_s2d`.
    stem_s2d: bool = False
    # Declarative mixed-precision policy (models.precision): resolves
    # every ConvBlock's param/compute dtypes + MXU matmul precision by
    # regex over the module path, and the trunk's entry/exit casts from
    # its compute/output dtypes.  None keeps the pre-policy ``dtype``
    # behavior (HLO-identical).
    policy: Optional[PrecisionPolicy] = None
    # Pallas stem fusion (ops.pallas_stem): route the VPU-bound stem
    # tail — both LRN layers plus the conv1/conv2 bias+ReLU(+pool)
    # epilogues — through the fused one-VMEM-pass kernels.  Bias-LRN
    # trunks only (the BN trunk has neither LRN nor conv biases);
    # parameter tree unchanged, interpret-mode parity-tested on CPU.
    pallas_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        use_lrn = self.use_lrn and not self.use_bn
        fuse_stem = self.pallas_stem and not self.use_bn
        compute_dtype = (self.policy.compute_dtype
                         if self.policy is not None else self.dtype)
        lrn_impl = "pallas" if fuse_stem else "xla"
        x = x.astype(compute_dtype)
        if self.stem_s2d:
            x = space_to_depth(x, 2)
            x = ConvBlock(
                64, (4, 4), (1, 1), padding=((1, 2), (1, 2)),
                dtype=self.dtype, use_bn=self.use_bn, policy=self.policy,
                fused_epilogue=fuse_stem,
                fuse_pool=(3, 2) if fuse_stem else None,
                name="conv1",
            )(x, train)
        else:
            x = ConvBlock(
                64, (7, 7), (2, 2),
                padding=((3, 3), (3, 3)) if self.caffe_pad else "SAME",
                dtype=self.dtype, use_bn=self.use_bn, policy=self.policy,
                fused_epilogue=fuse_stem,
                fuse_pool=(3, 2) if fuse_stem else None,
                name="conv1",
            )(x, train)
        if not fuse_stem:
            x = max_pool(x, 3, 2)
        if use_lrn:
            # named_scope: LRN is trunk-top-level code (not a flax
            # submodule), so without a scope its cost would land in the
            # root region of the prof report (obs.perf) instead of
            # being attributable — metadata only, the program is
            # unchanged.
            with jax.named_scope("lrn"):
                x = local_response_norm(x, impl=lrn_impl)
        x = ConvBlock(
            64, (1, 1), dtype=self.dtype, use_bn=self.use_bn,
            policy=self.policy, fused_epilogue=fuse_stem,
            name="conv2_reduce",
        )(x, train)
        x = ConvBlock(
            192, (3, 3), dtype=self.dtype, use_bn=self.use_bn,
            policy=self.policy, fused_epilogue=fuse_stem, name="conv2"
        )(x, train)
        if use_lrn:
            with jax.named_scope("lrn"):
                x = local_response_norm(x, impl=lrn_impl)
        x = max_pool(x, 3, 2)
        # nn.remat checkpoints the block boundary: only each block's
        # input survives to the backward, its internals recompute.
        # ``train`` (argnum 2; 0 is the module) must be static — it
        # selects the BN branch at trace time.
        incep_cls = (
            nn.remat(Inception, static_argnums=(2,))
            if self.remat else Inception
        )
        incep = lambda key: incep_cls(
            _INCEPTION_PLAN[key], self.dtype, self.use_bn,
            policy=self.policy,
            fuse_1x1=self.fuse_1x1, name=f"inception_{key}",
        )
        x = incep("3a")(x, train)
        x = incep("3b")(x, train)
        x = max_pool(x, 3, 2)
        for key in ("4a", "4b", "4c", "4d", "4e"):
            x = incep(key)(x, train)
        x = max_pool(x, 3, 2)
        x = incep("5a")(x, train)
        x = incep("5b")(x, train)
        x = global_avg_pool(x)  # pool5/7x7_s1 -> (N, 1024)
        x = x.astype(self.policy.output_dtype
                     if self.policy is not None else jnp.float32)
        if self.normalize:
            x = l2_normalize(x)
        return x



def fuse_inception_1x1_params(params, batch_stats=None):
    """Convert plain-trunk variables to the ``fuse_1x1=True`` layout.

    Exact: the fused conv's kernel/bias (and BN scale/bias/mean/var —
    all per-output-channel) are the channel-wise concatenation of
    b1x1 ++ b3x3_reduce ++ b5x5_reduce, in the slice order
    ``Inception.__call__`` uses.  Returns (params, batch_stats) with
    the three branch entries replaced by one ``fused_1x1`` entry;
    ``batch_stats`` may be None (bias/LRN trunk).
    """
    import jax

    def convert_tree(tree):
        if tree is None:
            return None
        out = jax.tree_util.tree_map(lambda x: x, tree)  # deep-ish copy
        for block, sub in list(out.items()):
            if not block.startswith("inception_") or "b1x1" not in sub:
                continue
            parts = [sub.pop("b1x1"), sub.pop("b3x3_reduce"),
                     sub.pop("b5x5_reduce")]
            fused = {}
            for mod in parts[0]:  # "Conv_0" and, for BN trunks, "BatchNorm_0"
                fused[mod] = {
                    leaf: jnp.concatenate(
                        [p[mod][leaf] for p in parts], axis=-1
                    )
                    for leaf in parts[0][mod]
                }
            sub["fused_1x1"] = fused
        return out

    return convert_tree(params), convert_tree(batch_stats)
