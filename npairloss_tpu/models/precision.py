"""Declarative mixed-precision policy for the model zoo.

One :class:`PrecisionPolicy` object answers, for every module in a
trunk, the three questions the MXU cares about: what dtype are the
parameters stored in, what dtype does the module compute in, and which
MXU precision mode do its gemms/convs run at.  Modules resolve their
answer by regex-matching their own flax module path against the
policy's ``rules`` — the same first-match-wins pattern partition-rule
systems use for sharding (SNIPPETS.md [3] ``match_partition_rules``) —
falling back to the policy-wide defaults.  This replaces the ad-hoc
``dtype=`` constructor split (``googlenet`` vs ``googlenet_mxu`` vs
``--bf16``) with one named, inspectable object threaded through
``models.get_model``, the trunk modules, and ``train.Solver``.

Shipped policies (``get_policy`` / ``available_policies``):

* ``"mxu"`` — THE FLAGSHIP DEFAULT.  bf16 compute over fp32 master
  params, explicit single-pass bf16 MXU precision on every conv/dense,
  and the loss engines' gemms in the same single-pass mode
  (``loss_matmul_precision="default"`` — the measured ring-bf16 row is
  6.7x the HIGHEST mode at pool 4096, BENCH_r05).  Normalization
  arithmetic (LRN / LayerNorm / BatchNorm statistics, L2 normalize)
  stays fp32 — that is a property of the module implementations, which
  compute their statistics in fp32 regardless of the activation dtype.
  The policy/fp32 loss delta is bounded by test
  (tests/test_precision_policy.py) and reported by bench.py.
* ``"bf16"`` — the pre-policy headline: bf16 compute, fp32 params,
  backend-default conv precision, oracle-parity (HIGHEST) loss gemms.
  Byte-compatible with the old ``dtype=jnp.bfloat16`` constructors.
* ``"fp32_parity"`` — the prototxt-parity fallback: fp32 everything,
  oracle-parity loss gemms.  HLO-identical to the pre-policy fp32
  trunk; this is the reference point every loss-delta bound in the
  test suite compares against.

Rules example (how a policy would pin one module family)::

    PrecisionPolicy(
        name="mxu_fp32stem",
        rules=(
            # conv1 keeps fp32 compute; everything else inherits the
            # policy-wide bf16 defaults.
            (r"(^|/)conv1(/|$)", {"compute_dtype": jnp.float32}),
        ),
    )

This module deliberately imports no sibling model code (the trunks
import *it*), and resolving a policy never touches jax state — it is a
pure description consumed at trace time.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

# The overridable per-module fields a rule may set.
_RULE_FIELDS = ("param_dtype", "compute_dtype", "matmul_precision")

# matmul_precision vocabulary: None = leave unset (the backend default),
# "default" = single-pass bf16-multiply/fp32-accumulate MXU mode,
# "highest" = full-fp32 multi-pass decomposition (oracle parity).  Same
# vocabulary as ops.npair_loss.resolve_matmul_precision, with None
# meaning "don't pass a precision at all" here (flax modules treat an
# explicit None the same way, so the distinction is only documentary).
_PRECISIONS = {
    None: None,
    "default": jax.lax.Precision.DEFAULT,
    "highest": jax.lax.Precision.HIGHEST,
}


@dataclasses.dataclass(frozen=True)
class ModulePrecision:
    """The resolved answer for ONE module: what ``nn.Conv``/``nn.Dense``
    should be constructed with."""

    param_dtype: Any
    compute_dtype: Any
    matmul_precision: Optional[str]

    @property
    def precision(self) -> Optional[jax.lax.Precision]:
        """The ``precision=`` argument for flax/lax ops (None = unset)."""
        return _PRECISIONS[self.matmul_precision]


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Declarative mixed-precision recipe for a whole trunk.

    ``rules`` is an ordered tuple of ``(regex, overrides)`` pairs
    matched (``re.search``) against the "/"-joined flax module path;
    the FIRST match wins and its overrides replace the policy-wide
    defaults for that module.  ``loss_matmul_precision`` is what the
    Solver hands the loss engines when the caller does not set
    ``matmul_precision`` explicitly (None = HIGHEST there — see
    ops.npair_loss.resolve_matmul_precision).
    """

    name: str
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    output_dtype: Any = jnp.float32
    matmul_precision: Optional[str] = None
    loss_matmul_precision: Optional[str] = None
    rules: Tuple[Tuple[str, Mapping[str, Any]], ...] = ()

    def __post_init__(self):
        for field, prec in (
            ("matmul_precision", self.matmul_precision),
            ("loss_matmul_precision", self.loss_matmul_precision),
        ):
            if prec not in _PRECISIONS:
                raise ValueError(
                    f"{field} must be one of "
                    f"{sorted(k for k in _PRECISIONS if k)} or None, "
                    f"got {prec!r}")
        for pat, over in self.rules:
            re.compile(pat)  # surface a bad regex at construction
            unknown = set(over) - set(_RULE_FIELDS)
            if unknown:
                raise ValueError(
                    f"rule {pat!r} sets unknown field(s) "
                    f"{sorted(unknown)}; allowed: {_RULE_FIELDS}")
            if "matmul_precision" in over and \
                    over["matmul_precision"] not in _PRECISIONS:
                raise ValueError(
                    f"rule {pat!r}: matmul_precision "
                    f"{over['matmul_precision']!r} not in "
                    f"{sorted(k for k in _PRECISIONS if k)}")

    def resolve(self, path: Union[str, Sequence[str], None]
                ) -> ModulePrecision:
        """Per-module precision for the module at ``path`` (a flax
        ``Module.path`` tuple or an already-joined string); first
        matching rule wins, else the policy-wide defaults."""
        name = path if isinstance(path, str) else "/".join(path or ())
        base = {
            "param_dtype": self.param_dtype,
            "compute_dtype": self.compute_dtype,
            "matmul_precision": self.matmul_precision,
        }
        for pat, over in self.rules:
            if re.search(pat, name) is not None:
                base.update(over)
                break
        return ModulePrecision(**base)

    def describe(self) -> Dict[str, Any]:
        """JSON-able summary (run manifests, bench records)."""
        return {
            "name": self.name,
            "param_dtype": jnp.dtype(self.param_dtype).name,
            "compute_dtype": jnp.dtype(self.compute_dtype).name,
            "output_dtype": jnp.dtype(self.output_dtype).name,
            "matmul_precision": self.matmul_precision,
            "loss_matmul_precision": self.loss_matmul_precision,
            "rules": [[pat, dict(over)] for pat, over in self.rules],
        }


# -- registry ----------------------------------------------------------------

_POLICIES: Dict[str, PrecisionPolicy] = {
    # The flagship default: wide single-pass bf16 gemms everywhere the
    # MXU runs, fp32 master params/updates, fp32 normalization (module-
    # internal).  The TPU-v4 paper (PAPERS.md) is explicit that this is
    # what the MXU rewards; googlenet_mxu at 21.91 ms vs 27.85 ms
    # (BENCH_r05) is this repo's measured evidence.
    "mxu": PrecisionPolicy(
        name="mxu",
        param_dtype=jnp.float32,
        compute_dtype=jnp.bfloat16,
        output_dtype=jnp.float32,
        matmul_precision="default",
        loss_matmul_precision="default",
    ),
    # The pre-policy bf16 headline, as a named object: bf16 compute,
    # backend-default conv precision, oracle-parity loss gemms.
    "bf16": PrecisionPolicy(
        name="bf16",
        param_dtype=jnp.float32,
        compute_dtype=jnp.bfloat16,
        output_dtype=jnp.float32,
        matmul_precision=None,
        loss_matmul_precision=None,
    ),
    # Prototxt-parity fallback: what every oracle/golden test compares
    # against.  HLO-identical to the pre-policy fp32 trunk.
    "fp32_parity": PrecisionPolicy(
        name="fp32_parity",
        param_dtype=jnp.float32,
        compute_dtype=jnp.float32,
        output_dtype=jnp.float32,
        matmul_precision=None,
        loss_matmul_precision=None,
    ),
}

# The policy the flagship workload (bench headline, CLI default when
# --precision is not given but a policy-aware entry point wants one)
# runs under.
DEFAULT_POLICY = "mxu"


def get_policy(name: Union[str, PrecisionPolicy]) -> PrecisionPolicy:
    """Resolve a policy name (or pass a policy through).  Unknown names
    raise with the known vocabulary — the CLI argparse choices and
    bench row validation both build on this being loud."""
    if isinstance(name, PrecisionPolicy):
        return name
    key = str(name).lower()
    if key not in _POLICIES:
        raise KeyError(
            f"unknown precision policy {name!r}; have "
            f"{sorted(_POLICIES)}")
    return _POLICIES[key]


def available_policies() -> Sequence[str]:
    return sorted(_POLICIES)


def module_precision(policy: Optional[PrecisionPolicy],
                     path: Union[str, Sequence[str], None],
                     fallback_dtype: Any) -> ModulePrecision:
    """The one resolution helper modules call: with no policy attached,
    reproduce the pre-policy behavior exactly (``fallback_dtype``
    compute over fp32 params, no explicit precision) so a policy-less
    build stays HLO-identical to the old constructors."""
    if policy is None:
        return ModulePrecision(
            param_dtype=jnp.float32,
            compute_dtype=fallback_dtype,
            matmul_precision=None,
        )
    return policy.resolve(path)
