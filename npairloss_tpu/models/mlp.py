"""Small MLP embedding net — the integration-test / smoke model."""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from npairloss_tpu.ops.normalize import l2_normalize


class MLPEmbedding(nn.Module):
    hidden: Sequence[int] = (128,)
    embedding_dim: int = 64
    dtype: Any = jnp.float32
    normalize: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = x.reshape(x.shape[0], -1)
        for i, h in enumerate(self.hidden):
            x = nn.relu(nn.Dense(h, dtype=self.dtype, name=f"dense{i}")(x))
        x = nn.Dense(self.embedding_dim, dtype=self.dtype, name="head")(x)
        x = x.astype(jnp.float32)
        if self.normalize:
            x = l2_normalize(x)
        return x
