"""ResNet embedding backbones in Flax (BASELINE.json: ResNet-50 on SOP).

Fresh NHWC implementation: bottleneck-v1 with BatchNorm, bf16 activations,
fp32 norm statistics — the standard TPU recipe.  Embedding = global average
pool of the final stage, optionally L2-normalized like the reference head.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from npairloss_tpu.models.layers import space_to_depth
from npairloss_tpu.ops.normalize import l2_normalize


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = lambda name: nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=name,
        )
        conv = lambda f, k, s, name: nn.Conv(
            f, (k, k), strides=(s, s), padding="SAME", use_bias=False,
            dtype=self.dtype, kernel_init=nn.initializers.he_normal(), name=name,
        )
        residual = x
        y = nn.relu(norm("bn1")(conv(self.features, 1, 1, "conv1")(x)))
        y = nn.relu(norm("bn2")(conv(self.features, 3, self.strides, "conv2")(y)))
        y = norm("bn3")(conv(self.features * 4, 1, 1, "conv3")(y))
        if residual.shape[-1] != y.shape[-1] or self.strides != 1:
            residual = norm("bn_proj")(
                conv(self.features * 4, 1, self.strides, "conv_proj")(residual)
            )
        return nn.relu(y + residual)


class ResNetEmbedding(nn.Module):
    """ResNet-v1 embedding net; ``stage_sizes=(3,4,6,3)`` is ResNet-50."""

    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    width: int = 64
    dtype: Any = jnp.bfloat16
    normalize: bool = True
    # Space-to-depth stem: exact rewrite of the 7x7/s2 C_in=3 conv as
    # s2d(2) + 4x4/s1 over 12 channels for MXU tiling — same math as
    # googlenet.stem_s2d (weights convert via conv1_kernel_to_s2d).
    stem_s2d: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if self.stem_s2d:
            x = space_to_depth(x, 2)
            x = nn.Conv(
                self.width, (4, 4), strides=(1, 1),
                padding=((1, 2), (1, 2)), use_bias=False, dtype=self.dtype,
                kernel_init=nn.initializers.he_normal(), name="conv_stem",
            )(x)
        else:
            x = nn.Conv(
                self.width, (7, 7), strides=(2, 2), padding="SAME",
                use_bias=False, dtype=self.dtype,
                kernel_init=nn.initializers.he_normal(), name="conv_stem",
            )(x)
        x = nn.relu(
            nn.BatchNorm(
                use_running_average=not train, momentum=0.9, dtype=self.dtype,
                param_dtype=jnp.float32, name="bn_stem",
            )(x)
        )
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, num_blocks in enumerate(self.stage_sizes):
            for block in range(num_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = Bottleneck(
                    self.width * (2**stage), strides, self.dtype,
                    name=f"stage{stage+1}_block{block+1}",
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        if self.normalize:
            x = l2_normalize(x)
        return x
