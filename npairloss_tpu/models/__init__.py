"""Embedding model zoo.

The reference trains a GoogLeNet trunk truncated at pool5 with an
L2-normalized embedding (usage/def.prototxt); BASELINE.json adds ResNet-50
and ViT-B/16 configs.  ``get_model(name)`` is the registry the config
front-end and trainer resolve through.

``get_model(name, policy=...)`` threads a declarative mixed-precision
policy (models.precision: "mxu" / "bf16" / "fp32_parity" or a
PrecisionPolicy object) through the trunk: policy-aware trunks
(GoogLeNet family, ViT) resolve per-module dtypes/precision by regex
over their module paths; the rest honor the policy's compute dtype.
The FLAGSHIP trunk+policy pair — what bench.py headlines and the CLI
defaults to for ``--precision mxu`` runs — is ``googlenet_mxu`` under
the ``"mxu"`` policy (FLAGSHIP_TRUNK / FLAGSHIP_POLICY below).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Union

from npairloss_tpu.models.googlenet import (
    GoogLeNetEmbedding,
    fuse_inception_1x1_params,
)
from npairloss_tpu.models.mlp import MLPEmbedding
from npairloss_tpu.models.precision import (
    DEFAULT_POLICY,
    PrecisionPolicy,
    available_policies,
    get_policy,
)
from npairloss_tpu.models.resnet import ResNetEmbedding
from npairloss_tpu.models.vit import ViTEmbedding

# The flagship workload's trunk + policy: the parity-preserving MXU
# rewrites (s2d stem + fused inception 1x1s — measured 21.91 ms vs the
# prototxt trunk's 27.85 ms, BENCH_r05) under the single-pass-bf16
# mixed-precision policy.  One home, so bench.py, the CLI, and the
# tests agree on what "flagship" means.
FLAGSHIP_TRUNK = "googlenet_mxu"
FLAGSHIP_POLICY = DEFAULT_POLICY

_REGISTRY: Dict[str, Callable[..., Any]] = {
    "googlenet": GoogLeNetEmbedding,
    "googlenet_embedding": GoogLeNetEmbedding,
    # Inception-BN: the from-scratch-trainable GoogLeNet (BN after every
    # conv, no LRN) — use for training runs without pretrained weights.
    "googlenet_bn": lambda **kw: GoogLeNetEmbedding(use_bn=True, **kw),
    "inception_bn": lambda **kw: GoogLeNetEmbedding(use_bn=True, **kw),
    # Space-to-depth stem: algebraically identical trunk with the 7x7/s2
    # C_in=3 stem rewritten for MXU tiling (see googlenet.stem_s2d);
    # weights interchange with the plain trunk via conv1_kernel_to_s2d.
    "googlenet_s2d": lambda **kw: GoogLeNetEmbedding(stem_s2d=True, **kw),
    "googlenet_bn_s2d": lambda **kw: GoogLeNetEmbedding(
        use_bn=True, stem_s2d=True, **kw
    ),
    # Fused inception 1x1s (exact algebra, MXU lane occupancy — see
    # googlenet.Inception.fuse_1x1); weights interchange with the plain
    # trunk via fuse_inception_1x1_params.  "_mxu" stacks both
    # parity-preserving rewrites (s2d stem + fused 1x1s).
    "googlenet_fused": lambda **kw: GoogLeNetEmbedding(fuse_1x1=True, **kw),
    "googlenet_mxu": lambda **kw: GoogLeNetEmbedding(
        stem_s2d=True, fuse_1x1=True, **kw
    ),
    # Pallas stem fusion on top of the MXU rewrites: fused LRN +
    # conv-bias-ReLU(+pool) epilogues (ops.pallas_stem; interpret-mode
    # parity-tested).  Parameter tree identical to googlenet_mxu.
    "googlenet_pallas": lambda **kw: GoogLeNetEmbedding(
        stem_s2d=True, fuse_1x1=True, pallas_stem=True, **kw
    ),
    # The headline trunk by its workload name: resolved THROUGH
    # FLAGSHIP_TRUNK at call time, so repointing the flagship repoints
    # --model flagship with it (a copy-pasted constructor here would
    # silently drift).
    "flagship": lambda **kw: _REGISTRY[FLAGSHIP_TRUNK](**kw),
    "resnet50": lambda **kw: ResNetEmbedding(stage_sizes=(3, 4, 6, 3), **kw),
    "resnet50_s2d": lambda **kw: ResNetEmbedding(
        stage_sizes=(3, 4, 6, 3), stem_s2d=True, **kw
    ),
    "resnet18": lambda **kw: ResNetEmbedding(stage_sizes=(2, 2, 2, 2), width=64, **kw),
    "vit_b16": ViTEmbedding,
    "mlp": MLPEmbedding,
}


# Registry names whose trunks thread the policy object all the way to
# per-module resolution; the rest (mlp, resnet) honor its compute dtype
# only.  Kept explicit so a silently-dropped policy is impossible — a
# new policy-aware trunk must be listed here to receive the object.
_POLICY_AWARE = {
    "googlenet", "googlenet_embedding", "googlenet_bn", "inception_bn",
    "googlenet_s2d", "googlenet_bn_s2d", "googlenet_fused",
    "googlenet_mxu", "googlenet_pallas", "flagship", "vit_b16",
}


def get_model(name: str,
              policy: Optional[Union[str, PrecisionPolicy]] = None,
              **kwargs):
    key = name.lower()
    if key not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    if policy is not None:
        pol = get_policy(policy)
        kwargs.setdefault("dtype", pol.compute_dtype)
        if key in _POLICY_AWARE:
            kwargs["policy"] = pol
    return _REGISTRY[key](**kwargs)


def flagship_model(policy: Optional[Union[str, PrecisionPolicy]] =
                   FLAGSHIP_POLICY, **kwargs):
    """The headline trunk under the default (or given) policy — the ONE
    constructor bench.py, the CLI flagship paths, and the tests share."""
    return get_model(FLAGSHIP_TRUNK, policy=policy, **kwargs)


def jit_init(model, key, example_input, train: bool = False, **kwargs):
    """flax ``model.init`` as ONE compiled program.

    Eager init issues hundreds of small per-op dispatches; on a tunneled
    backend each costs ~a full round-trip, and a burst of them has
    wedged the tunnel outright (docs/DESIGN.md §6).  Every init that can
    run against real hardware should go through here.
    """
    import jax

    return jax.jit(
        lambda k, x: model.init(k, x, train=train, **kwargs)
    )(key, example_input)


def available_models():
    return sorted(_REGISTRY)
