"""Embedding model zoo.

The reference trains a GoogLeNet trunk truncated at pool5 with an
L2-normalized embedding (usage/def.prototxt); BASELINE.json adds ResNet-50
and ViT-B/16 configs.  ``get_model(name)`` is the registry the config
front-end and trainer resolve through.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

from npairloss_tpu.models.googlenet import (
    GoogLeNetEmbedding,
    fuse_inception_1x1_params,
)
from npairloss_tpu.models.mlp import MLPEmbedding
from npairloss_tpu.models.resnet import ResNetEmbedding
from npairloss_tpu.models.vit import ViTEmbedding

_REGISTRY: Dict[str, Callable[..., Any]] = {
    "googlenet": GoogLeNetEmbedding,
    "googlenet_embedding": GoogLeNetEmbedding,
    # Inception-BN: the from-scratch-trainable GoogLeNet (BN after every
    # conv, no LRN) — use for training runs without pretrained weights.
    "googlenet_bn": lambda **kw: GoogLeNetEmbedding(use_bn=True, **kw),
    "inception_bn": lambda **kw: GoogLeNetEmbedding(use_bn=True, **kw),
    # Space-to-depth stem: algebraically identical trunk with the 7x7/s2
    # C_in=3 stem rewritten for MXU tiling (see googlenet.stem_s2d);
    # weights interchange with the plain trunk via conv1_kernel_to_s2d.
    "googlenet_s2d": lambda **kw: GoogLeNetEmbedding(stem_s2d=True, **kw),
    "googlenet_bn_s2d": lambda **kw: GoogLeNetEmbedding(
        use_bn=True, stem_s2d=True, **kw
    ),
    # Fused inception 1x1s (exact algebra, MXU lane occupancy — see
    # googlenet.Inception.fuse_1x1); weights interchange with the plain
    # trunk via fuse_inception_1x1_params.  "_mxu" stacks both
    # parity-preserving rewrites (s2d stem + fused 1x1s).
    "googlenet_fused": lambda **kw: GoogLeNetEmbedding(fuse_1x1=True, **kw),
    "googlenet_mxu": lambda **kw: GoogLeNetEmbedding(
        stem_s2d=True, fuse_1x1=True, **kw
    ),
    "resnet50": lambda **kw: ResNetEmbedding(stage_sizes=(3, 4, 6, 3), **kw),
    "resnet50_s2d": lambda **kw: ResNetEmbedding(
        stage_sizes=(3, 4, 6, 3), stem_s2d=True, **kw
    ),
    "resnet18": lambda **kw: ResNetEmbedding(stage_sizes=(2, 2, 2, 2), width=64, **kw),
    "vit_b16": ViTEmbedding,
    "mlp": MLPEmbedding,
}


def get_model(name: str, **kwargs):
    key = name.lower()
    if key not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[key](**kwargs)


def jit_init(model, key, example_input, train: bool = False, **kwargs):
    """flax ``model.init`` as ONE compiled program.

    Eager init issues hundreds of small per-op dispatches; on a tunneled
    backend each costs ~a full round-trip, and a burst of them has
    wedged the tunnel outright (docs/DESIGN.md §6).  Every init that can
    run against real hardware should go through here.
    """
    import jax

    return jax.jit(
        lambda k, x: model.init(k, x, train=train, **kwargs)
    )(key, example_input)


def available_models():
    return sorted(_REGISTRY)
