"""ViT embedding backbone (BASELINE.json stretch config: ViT-B/16, 32k-batch
N-pair contrastive — the CLIP-style negative pool over ICI).

Fresh Flax implementation: patchify-as-conv (MXU-friendly), pre-LN
transformer blocks, bf16 activations / fp32 layernorm, CLS-token embedding,
optionally L2-normalized.  The mixed-precision policy (models.precision)
threads through every Dense/attention/patchify gemm — each module
regex-resolves its own path — while the LayerNorms stay fp32 regardless
(their statistics are fp32 by construction below).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from npairloss_tpu.models.precision import PrecisionPolicy, module_precision
from npairloss_tpu.ops.normalize import l2_normalize


class MlpBlock(nn.Module):
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    policy: Optional[PrecisionPolicy] = None

    @nn.compact
    def __call__(self, x):
        mp = module_precision(self.policy, self.path, self.dtype)
        d = x.shape[-1]
        dense = lambda f: nn.Dense(
            f, dtype=mp.compute_dtype, param_dtype=mp.param_dtype,
            precision=mp.precision,
        )
        x = dense(self.mlp_dim)(x)
        x = nn.gelu(x)
        return dense(d)(x)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    policy: Optional[PrecisionPolicy] = None

    @nn.compact
    def __call__(self, x):
        # Resolve at the NAMED submodule's path ("blockN/attn"), not
        # this block's, so per-module rules targeting the attention
        # actually match (nn.MultiHeadDotProductAttention cannot
        # resolve itself — it predates the policy).
        mp = module_precision(self.policy, (*self.path, "attn"),
                              self.dtype)
        ln = lambda name: nn.LayerNorm(dtype=jnp.float32, name=name)
        y = ln("ln1")(x).astype(mp.compute_dtype)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, dtype=mp.compute_dtype,
            param_dtype=mp.param_dtype, precision=mp.precision, name="attn",
        )(y, y)
        x = x + y
        y = ln("ln2")(x).astype(mp.compute_dtype)
        return x + MlpBlock(self.mlp_dim, self.dtype, policy=self.policy,
                            name="mlp")(y)


class ViTEmbedding(nn.Module):
    """ViT trunk -> CLS embedding.  Defaults are ViT-B/16."""

    patch: int = 16
    hidden: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dtype: Any = jnp.bfloat16
    normalize: bool = True
    policy: Optional[PrecisionPolicy] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        # Resolved at the patchify conv's own path (see EncoderBlock).
        mp = module_precision(self.policy, (*self.path, "patchify"),
                              self.dtype)
        n = x.shape[0]
        x = nn.Conv(
            self.hidden,
            (self.patch, self.patch),
            strides=(self.patch, self.patch),
            padding="VALID",
            dtype=mp.compute_dtype,
            param_dtype=mp.param_dtype,
            precision=mp.precision,
            name="patchify",
        )(x.astype(mp.compute_dtype))
        x = x.reshape(n, -1, self.hidden)
        cls = self.param(
            "cls", nn.initializers.zeros, (1, 1, self.hidden), jnp.float32
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (n, 1, self.hidden)).astype(
                mp.compute_dtype), x],
            axis=1,
        )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, x.shape[1], self.hidden),
            jnp.float32,
        )
        x = x + pos.astype(mp.compute_dtype)
        for i in range(self.depth):
            x = EncoderBlock(
                self.num_heads, self.mlp_dim, self.dtype,
                policy=self.policy, name=f"block{i}"
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        out_dtype = (self.policy.output_dtype
                     if self.policy is not None else jnp.float32)
        emb = x[:, 0].astype(out_dtype)
        if self.normalize:
            emb = l2_normalize(emb)
        return emb
