"""ViT embedding backbone (BASELINE.json stretch config: ViT-B/16, 32k-batch
N-pair contrastive — the CLIP-style negative pool over ICI).

Fresh Flax implementation: patchify-as-conv (MXU-friendly), pre-LN
transformer blocks, bf16 activations / fp32 layernorm, CLS-token embedding,
optionally L2-normalized.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from npairloss_tpu.ops.normalize import l2_normalize


class MlpBlock(nn.Module):
    mlp_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        x = nn.Dense(self.mlp_dim, dtype=self.dtype)(x)
        x = nn.gelu(x)
        return nn.Dense(d, dtype=self.dtype)(x)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        ln = lambda name: nn.LayerNorm(dtype=jnp.float32, name=name)
        y = ln("ln1")(x).astype(self.dtype)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, dtype=self.dtype, name="attn"
        )(y, y)
        x = x + y
        y = ln("ln2")(x).astype(self.dtype)
        return x + MlpBlock(self.mlp_dim, self.dtype, name="mlp")(y)


class ViTEmbedding(nn.Module):
    """ViT trunk -> CLS embedding.  Defaults are ViT-B/16."""

    patch: int = 16
    hidden: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dtype: Any = jnp.bfloat16
    normalize: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        n = x.shape[0]
        x = nn.Conv(
            self.hidden,
            (self.patch, self.patch),
            strides=(self.patch, self.patch),
            padding="VALID",
            dtype=self.dtype,
            name="patchify",
        )(x.astype(self.dtype))
        x = x.reshape(n, -1, self.hidden)
        cls = self.param(
            "cls", nn.initializers.zeros, (1, 1, self.hidden), jnp.float32
        )
        x = jnp.concatenate([jnp.broadcast_to(cls, (n, 1, self.hidden)).astype(self.dtype), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, x.shape[1], self.hidden),
            jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        for i in range(self.depth):
            x = EncoderBlock(
                self.num_heads, self.mlp_dim, self.dtype, name=f"block{i}"
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        emb = x[:, 0].astype(jnp.float32)
        if self.normalize:
            emb = l2_normalize(emb)
        return emb
