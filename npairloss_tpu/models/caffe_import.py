"""Caffe <-> Flax weight migration for the GoogLeNet trunk.

The reference is a layer inside a Caffe fork; its users' trained assets
are ``.caffemodel`` files over the standard bvlc_googlenet layer names
(the reference net template spells out ``conv1/7x7_s2`` and elides the
canonical middle, usage/def.prototxt:85-111).  This module maps those
blobs onto ``models.googlenet.GoogLeNetEmbedding`` parameters — and
back, so a trunk finetuned here can be deployed into an existing Caffe
retrieval stack.

Layout notes:
  * Caffe conv kernels are OIHW; Flax wants HWIO — ``transpose(2,3,1,0)``.
  * Both run cross-correlation (no kernel flip): the weights carry over
    directly.
  * Stem-geometry caveat: Caffe pads conv1 symmetrically (pad: 3)
    while this trunk's default SAME pads (2, 3) at even inputs — with
    stride 2 that is a one-input-pixel PHASE shift of the sampling
    grid, not just a border effect.  For closest-to-Caffe inference on
    imported weights use ``GoogLeNetEmbedding(caffe_pad=True)`` (CLI
    ``--caffe-pad``), which evaluates conv1 at exactly Caffe's
    geometry (pinned by test); pool layers already agree.
  * Only the embedding trunk (through pool5/7x7_s1) migrates: the
    reference's aux-classifier heads (loss1/*, loss2/*, loss3/fc...)
    have no counterpart in the metric-learning deployment and are
    ignored on import.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

# Our param-tree block name -> caffe layer name.
_STEM = {
    "conv1": "conv1/7x7_s2",
    "conv2_reduce": "conv2/3x3_reduce",
    "conv2": "conv2/3x3",
}
_BRANCH = {
    "b1x1": "1x1",
    "b3x3_reduce": "3x3_reduce",
    "b3x3": "3x3",
    "b5x5_reduce": "5x5_reduce",
    "b5x5": "5x5",
    "pool_proj": "pool_proj",
}
_STAGES = ("3a", "3b", "4a", "4b", "4c", "4d", "4e", "5a", "5b")


def caffe_layer_map() -> Dict[str, str]:
    """{(our block path "inception_3a/b1x1" | "conv1") : caffe name}."""
    out = dict(_STEM)
    for stage in _STAGES:
        for ours, theirs in _BRANCH.items():
            out[f"inception_{stage}/{ours}"] = f"inception_{stage}/{theirs}"
    return out


def googlenet_params_from_caffemodel(
    blobs: Dict[str, List[np.ndarray]], params,
):
    """New params for ``GoogLeNetEmbedding`` from caffemodel blobs.

    ``params`` is the target param tree (from ``model.init``) — used for
    shape validation and to carry any entries the caffemodel lacks.
    Raises KeyError/ValueError on missing layers or shape mismatches
    (silent partial loads corrupt finetunes).  Import the PLAIN trunk
    and apply `conv1_kernel_to_s2d` / `fuse_inception_1x1_params`
    afterwards for the MXU variants.
    """
    import jax

    new = jax.tree_util.tree_map(lambda x: x, params)
    for path, caffe_name in caffe_layer_map().items():
        if caffe_name not in blobs:
            raise KeyError(
                f"caffemodel is missing layer {caffe_name!r} "
                f"(wanted for {path})"
            )
        parts = path.split("/")
        node = new
        for p in parts:
            node = node[p]
        conv = node["Conv_0"]
        want = tuple(conv["kernel"].shape)  # HWIO
        k = np.asarray(blobs[caffe_name][0], dtype=np.float32)
        if k.ndim != 4:
            raise ValueError(
                f"{caffe_name}: kernel blob has shape {k.shape}, wanted 4-D"
            )
        k = k.transpose(2, 3, 1, 0)  # OIHW -> HWIO
        if tuple(k.shape) != want:
            raise ValueError(
                f"{caffe_name}: kernel {k.shape} vs model {want}"
            )
        conv["kernel"] = k
        if "bias" in conv:
            if len(blobs[caffe_name]) < 2:
                raise ValueError(f"{caffe_name}: missing bias blob")
            b = np.asarray(
                blobs[caffe_name][1], dtype=np.float32
            ).reshape(-1)
            if b.shape != tuple(conv["bias"].shape):
                raise ValueError(
                    f"{caffe_name}: bias {b.shape} vs model "
                    f"{conv['bias'].shape}"
                )
            conv["bias"] = b
    return new


def caffemodel_layers_from_googlenet_params(
    params,
) -> Dict[str, List[np.ndarray]]:
    """The reverse mapping: {caffe layer name: [kernel OIHW, bias]}.

    Feed to ``config.caffemodel.write_caffemodel`` to hand a trunk
    trained here back to a Caffe deployment."""
    out: Dict[str, List[np.ndarray]] = {}
    for path, caffe_name in caffe_layer_map().items():
        node = params
        for p in path.split("/"):
            node = node[p]
        conv = node["Conv_0"]
        k = np.asarray(conv["kernel"]).transpose(3, 2, 0, 1)  # HWIO -> OIHW
        blobs = [k.astype(np.float32)]
        if "bias" in conv:
            blobs.append(np.asarray(conv["bias"], dtype=np.float32))
        out[caffe_name] = blobs
    return out


# -- ResNet-50 (BASELINE.json config 3's trunk) -----------------------------
#
# Caffe ResNet-50 (the canonical release the reference era used) names
# convs ``res{stage}{letter}_branch{1,2a,2b,2c}`` with separate
# ``bn*`` (mean, var, scale_factor) and ``scale*`` (gamma, beta) layers;
# our trunk is models/resnet.py (conv_stem/bn_stem +
# stage{s}_block{b}/{conv1..3,conv_proj,bn1..3,bn_proj}).
#
# Stride caveat: Caffe ResNet-50 is v1 (stride 2 on the 1x1 branch2a);
# this trunk is v1.5-style (stride on the 3x3).  Kernel SHAPES are
# identical, so the weights migrate cleanly as a finetune init — the
# same shape-compatible transfer torchvision's v1.5 popularized.

_RESNET_BRANCH = {
    "conv1": "branch2a", "bn1": "branch2a",
    "conv2": "branch2b", "bn2": "branch2b",
    "conv3": "branch2c", "bn3": "branch2c",
    "conv_proj": "branch1", "bn_proj": "branch1",
}


def _resnet_block_names(stage_sizes=(3, 4, 6, 3)):
    """[(ours_block, caffe_block)] e.g. ("stage1_block1", "2a")."""
    out = []
    for s, n in enumerate(stage_sizes):
        for b in range(n):
            out.append((f"stage{s + 1}_block{b + 1}",
                        f"{s + 2}{chr(ord('a') + b)}"))
    return out


def _caffe_bn(blobs, bn_name, scale_name, want_c):
    """(scale, bias, mean, var) from a Caffe BatchNorm + Scale pair.

    Caffe's BatchNorm stores running sums times a scale_factor blob;
    gamma/beta live in the separate Scale layer."""
    if bn_name not in blobs:
        raise KeyError(f"caffemodel is missing layer {bn_name!r}")
    if scale_name not in blobs:
        raise KeyError(f"caffemodel is missing layer {scale_name!r}")
    bn = [np.asarray(b, np.float32).reshape(-1) for b in blobs[bn_name]]
    sc = [np.asarray(b, np.float32).reshape(-1) for b in blobs[scale_name]]
    if len(bn) < 2 or len(sc) < 2:
        raise ValueError(f"{bn_name}/{scale_name}: unexpected blob count")
    factor = float(bn[2][0]) if len(bn) > 2 and bn[2].size else 1.0
    factor = factor if factor != 0.0 else 1.0
    mean, var = bn[0] / factor, bn[1] / factor
    gamma, beta = sc[0], sc[1]
    for name, arr in (("mean", mean), ("var", var),
                      ("gamma", gamma), ("beta", beta)):
        if arr.shape != (want_c,):
            raise ValueError(
                f"{bn_name}: {name} has shape {arr.shape}, wanted ({want_c},)"
            )
    return gamma, beta, mean, var


def resnet50_params_from_caffemodel(blobs, params, batch_stats):
    """(params, batch_stats) for ``ResNetEmbedding(stage_sizes=(3,4,6,3))``
    from canonical Caffe ResNet-50 blobs.  Loud on missing layers and
    shape mismatches, like the GoogLeNet path."""
    import jax

    new_p = jax.tree_util.tree_map(lambda x: x, params)
    new_s = jax.tree_util.tree_map(lambda x: x, batch_stats)

    def set_conv(node, caffe_name):
        k = np.asarray(blobs[caffe_name][0], np.float32)
        if k.ndim != 4:
            raise ValueError(f"{caffe_name}: kernel {k.shape} not 4-D")
        k = k.transpose(2, 3, 1, 0)
        want = tuple(np.shape(node["kernel"]))
        if tuple(k.shape) != want:
            raise ValueError(f"{caffe_name}: kernel {k.shape} vs {want}")
        node["kernel"] = k

    def set_bn(p_node, s_node, bn_name, scale_name):
        c = int(np.shape(p_node["scale"])[0])
        gamma, beta, mean, var = _caffe_bn(blobs, bn_name, scale_name, c)
        p_node["scale"], p_node["bias"] = gamma, beta
        s_node["mean"], s_node["var"] = mean, var

    if "conv1" not in blobs:
        raise KeyError("caffemodel is missing layer 'conv1'")
    set_conv(new_p["conv_stem"], "conv1")
    set_bn(new_p["bn_stem"], new_s["bn_stem"], "bn_conv1", "scale_conv1")

    for ours_block, cb in _resnet_block_names():
        p_blk, s_blk = new_p[ours_block], new_s[ours_block]
        for ours, branch in _RESNET_BRANCH.items():
            if ours not in p_blk:
                continue  # non-proj blocks have no conv_proj/bn_proj
            if ours.startswith("conv"):
                name = f"res{cb}_{branch}"
                if name not in blobs:
                    raise KeyError(f"caffemodel is missing layer {name!r}")
                set_conv(p_blk[ours], name)
            else:
                set_bn(p_blk[ours], s_blk[ours],
                       f"bn{cb}_{branch}", f"scale{cb}_{branch}")
    return new_p, new_s


def caffemodel_layers_from_resnet50_params(params, batch_stats):
    """Reverse mapping: canonical Caffe ResNet-50 layer blobs
    (BatchNorm scale_factor written as 1)."""
    out: Dict[str, List[np.ndarray]] = {}

    def put(conv_node, bn_node, stats_node, conv_name, bn_name, scale_name):
        k = np.asarray(conv_node["kernel"], np.float32).transpose(3, 2, 0, 1)
        out[conv_name] = [k]
        out[bn_name] = [
            np.asarray(stats_node["mean"], np.float32),
            np.asarray(stats_node["var"], np.float32),
            np.ones((1,), np.float32),
        ]
        out[scale_name] = [
            np.asarray(bn_node["scale"], np.float32),
            np.asarray(bn_node["bias"], np.float32),
        ]

    put(params["conv_stem"], params["bn_stem"], batch_stats["bn_stem"],
        "conv1", "bn_conv1", "scale_conv1")
    for ours_block, cb in _resnet_block_names():
        p_blk, s_blk = params[ours_block], batch_stats[ours_block]
        for ours, branch in _RESNET_BRANCH.items():
            if ours not in p_blk or not ours.startswith("conv"):
                continue
            bn = ours.replace("conv", "bn")
            put(p_blk[ours], p_blk[bn], s_blk[bn],
                f"res{cb}_{branch}",
                f"bn{cb}_{branch}", f"scale{cb}_{branch}")
    return out


# -- SolverState history (optimizer-state migration) ------------------------
#
# Caffe's SGDSolver snapshots its momentum as SolverState.history: one
# BlobProto per learnable parameter, in net parameter order (layer order
# of the prototxt, weight then bias within a layer).  The GoogLeNet
# trunk's learnable params are exactly the conv kernels+biases that
# caffe_layer_map() enumerates, and our CaffeSGDState.momentum_buf tree
# mirrors the params tree — so the weight converters apply verbatim to
# momentum and define the canonical blob order.


def googlenet_history_from_momentum(momentum_params) -> List[np.ndarray]:
    """SolverState ``history`` blob list (net order, OIHW kernels) from a
    momentum tree shaped like the GoogLeNet params tree."""
    hist: List[np.ndarray] = []
    for blobs in caffemodel_layers_from_googlenet_params(
            momentum_params).values():
        hist.extend(blobs)
    return hist


def googlenet_momentum_from_history(history, momentum_template,
                                    strict: bool = False):
    """(momentum tree, skipped blob count) from SolverState ``history``.

    The reference's full training net carries aux-classifier heads
    (loss1/*, loss2/*) whose learnable params are INTERLEAVED with the
    trunk's in net order, so a genuine reference ``.solverstate`` has
    more history blobs than the embedding trunk.  Default mode aligns
    by shape-guided greedy matching: expected trunk blobs (OIHW kernel
    then bias per conv, layer-map order) consume history in order,
    skipping non-matching aux blobs — safe for the GoogLeNet+aux
    topology because within a layer the bias immediately follows its
    kernel (nothing can interpose), and across layers the skip scans
    for a 4-D kernel shape no aux blob shares.  ``strict=True`` demands
    an exact 1:1 sequence (round-trip tests / files this repo wrote).
    Every expected blob must be found and shapes are validated — a
    silent partial load would corrupt the resumed trajectory."""
    named: Dict[str, List[np.ndarray]] = {}
    i = 0
    skipped = 0
    for path, caffe_name in caffe_layer_map().items():
        node = momentum_template
        for p in path.split("/"):
            node = node[p]
        conv = node["Conv_0"]
        h, w, cin, cout = conv["kernel"].shape
        expect = [(cout, cin, h, w)]  # history kernels are OIHW
        if "bias" in conv:
            expect.append(tuple(conv["bias"].shape))

        def _matches(blob, shp):
            if len(shp) == 4:  # kernel: exact 4-D match
                return tuple(blob.shape) == shp
            # bias (n,): tolerate the legacy 4-D (1,1,1,n) blob storage
            # the weight path also accepts (old-Caffe forks write it).
            return blob.size == shp[0] and max(blob.shape) == blob.size

        blobs: List[np.ndarray] = []
        for shp in expect:
            while i < len(history) and not _matches(history[i], shp):
                if strict:
                    raise ValueError(
                        f"solverstate history blob {i} has shape "
                        f"{tuple(history[i].shape)}; layer "
                        f"{caffe_name!r} wanted {shp} (strict mode)"
                    )
                skipped += 1
                i += 1
            if i >= len(history):
                raise ValueError(
                    f"solverstate history exhausted at layer "
                    f"{caffe_name!r} (wanted shape {shp}) — "
                    f"{len(history)} blobs, {skipped} skipped"
                )
            blobs.append(np.asarray(history[i]))
            i += 1
        named[caffe_name] = blobs
    trailing = len(history) - i
    if trailing:
        if strict:
            raise ValueError(
                f"solverstate history has {trailing} trailing blobs the "
                "GoogLeNet trunk does not consume (strict mode)"
            )
        skipped += trailing
    return googlenet_params_from_caffemodel(named, momentum_template), \
        skipped
