"""Caffe <-> Flax weight migration for the GoogLeNet trunk.

The reference is a layer inside a Caffe fork; its users' trained assets
are ``.caffemodel`` files over the standard bvlc_googlenet layer names
(the reference net template spells out ``conv1/7x7_s2`` and elides the
canonical middle, usage/def.prototxt:85-111).  This module maps those
blobs onto ``models.googlenet.GoogLeNetEmbedding`` parameters — and
back, so a trunk finetuned here can be deployed into an existing Caffe
retrieval stack.

Layout notes:
  * Caffe conv kernels are OIHW; Flax wants HWIO — ``transpose(2,3,1,0)``.
  * Both run cross-correlation (no kernel flip): the weights carry over
    directly.
  * Boundary caveat: Caffe pads conv1 symmetrically (pad: 3) while this
    trunk uses SAME (pad (2,3) at 224/s2) — identical output shapes,
    border-pixel differences only.  Retrieval embeddings are robust to
    this; exact-parity work would pin explicit padding.
  * Only the embedding trunk (through pool5/7x7_s1) migrates: the
    reference's aux-classifier heads (loss1/*, loss2/*, loss3/fc...)
    have no counterpart in the metric-learning deployment and are
    ignored on import.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

# Our param-tree block name -> caffe layer name.
_STEM = {
    "conv1": "conv1/7x7_s2",
    "conv2_reduce": "conv2/3x3_reduce",
    "conv2": "conv2/3x3",
}
_BRANCH = {
    "b1x1": "1x1",
    "b3x3_reduce": "3x3_reduce",
    "b3x3": "3x3",
    "b5x5_reduce": "5x5_reduce",
    "b5x5": "5x5",
    "pool_proj": "pool_proj",
}
_STAGES = ("3a", "3b", "4a", "4b", "4c", "4d", "4e", "5a", "5b")


def caffe_layer_map() -> Dict[str, str]:
    """{(our block path "inception_3a/b1x1" | "conv1") : caffe name}."""
    out = dict(_STEM)
    for stage in _STAGES:
        for ours, theirs in _BRANCH.items():
            out[f"inception_{stage}/{ours}"] = f"inception_{stage}/{theirs}"
    return out


def googlenet_params_from_caffemodel(
    blobs: Dict[str, List[np.ndarray]], params,
):
    """New params for ``GoogLeNetEmbedding`` from caffemodel blobs.

    ``params`` is the target param tree (from ``model.init``) — used for
    shape validation and to carry any entries the caffemodel lacks.
    Raises KeyError/ValueError on missing layers or shape mismatches
    (silent partial loads corrupt finetunes).  Import the PLAIN trunk
    and apply `conv1_kernel_to_s2d` / `fuse_inception_1x1_params`
    afterwards for the MXU variants.
    """
    import jax

    new = jax.tree_util.tree_map(lambda x: x, params)
    for path, caffe_name in caffe_layer_map().items():
        if caffe_name not in blobs:
            raise KeyError(
                f"caffemodel is missing layer {caffe_name!r} "
                f"(wanted for {path})"
            )
        parts = path.split("/")
        node = new
        for p in parts:
            node = node[p]
        conv = node["Conv_0"]
        want = tuple(conv["kernel"].shape)  # HWIO
        k = np.asarray(blobs[caffe_name][0], dtype=np.float32)
        if k.ndim != 4:
            raise ValueError(
                f"{caffe_name}: kernel blob has shape {k.shape}, wanted 4-D"
            )
        k = k.transpose(2, 3, 1, 0)  # OIHW -> HWIO
        if tuple(k.shape) != want:
            raise ValueError(
                f"{caffe_name}: kernel {k.shape} vs model {want}"
            )
        conv["kernel"] = k
        if "bias" in conv:
            if len(blobs[caffe_name]) < 2:
                raise ValueError(f"{caffe_name}: missing bias blob")
            b = np.asarray(
                blobs[caffe_name][1], dtype=np.float32
            ).reshape(-1)
            if b.shape != tuple(conv["bias"].shape):
                raise ValueError(
                    f"{caffe_name}: bias {b.shape} vs model "
                    f"{conv['bias'].shape}"
                )
            conv["bias"] = b
    return new


def caffemodel_layers_from_googlenet_params(
    params,
) -> Dict[str, List[np.ndarray]]:
    """The reverse mapping: {caffe layer name: [kernel OIHW, bias]}.

    Feed to ``config.caffemodel.write_caffemodel`` to hand a trunk
    trained here back to a Caffe deployment."""
    out: Dict[str, List[np.ndarray]] = {}
    for path, caffe_name in caffe_layer_map().items():
        node = params
        for p in path.split("/"):
            node = node[p]
        conv = node["Conv_0"]
        k = np.asarray(conv["kernel"]).transpose(3, 2, 0, 1)  # HWIO -> OIHW
        blobs = [k.astype(np.float32)]
        if "bias" in conv:
            blobs.append(np.asarray(conv["bias"], dtype=np.float32))
        out[caffe_name] = blobs
    return out
