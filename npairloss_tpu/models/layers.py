"""Shared model building blocks (NHWC, bf16-friendly)."""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from npairloss_tpu.models.precision import (
    ModulePrecision,
    PrecisionPolicy,
    module_precision,
)


def local_response_norm(
    x: jax.Array,
    size: int = 5,
    alpha: float = 1e-4,
    beta: float = 0.75,
    k: float = 1.0,
    impl: str = "xla",
    cache: Optional[bool] = None,
) -> jax.Array:
    """Across-channel LRN (the classic GoogLeNet/AlexNet normalization).

    x: NHWC.  Matches Caffe LRN semantics: denominator
    (k + alpha/size * sum_{window} x^2)^beta over a channel window.

    ``impl="pallas"`` routes through the fused one-VMEM-pass kernel
    (ops.pallas_stem.fused_lrn — parity-tested against this reference);
    ``cache`` is its denominator-cache knob (None = auto by size).
    """
    if impl == "pallas":
        from npairloss_tpu.ops.pallas_stem import fused_lrn

        return fused_lrn(x, size, alpha, beta, k, cache=cache)
    if impl != "xla":
        raise ValueError(f"impl must be 'xla' or 'pallas', got {impl!r}")
    xf = x.astype(jnp.float32)
    sq = xf * xf
    win = jax.lax.reduce_window(
        sq,
        0.0,
        jax.lax.add,
        window_dimensions=(1, 1, 1, size),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (0, 0), (0, 0), (size // 2, size - 1 - size // 2)),
    )
    d = k + (alpha / size) * win
    if beta == 0.75:
        # The reference's beta: d^-0.75 == (sqrt(rsqrt(d)))^3, two fast
        # VPU ops + two mults instead of the exp+log a generic pow
        # lowers to.  LRN is ~25% of the flagship step
        # (profile/flagship.json: full - no_lrn = 6.9 ms), so the
        # transcendental on every activation element matters.  Differs
        # from pow by a few float32 ulp — inside oracle tolerance
        # (tests/test_models.py LRN parity).
        r = jnp.sqrt(jax.lax.rsqrt(d))
        out = xf * (r * r * r)
    else:
        out = xf / jnp.power(d, beta)
    return out.astype(x.dtype)


class _EpilogueConv(nn.Module):
    """``nn.Conv``-compatible parameter tree (``kernel`` + ``bias``)
    that returns the PRE-BIAS conv output and the bias separately, so a
    Pallas epilogue (ops.pallas_stem) can fuse bias + ReLU (+ pool) in
    one VMEM pass.  Named ``Conv_0`` by the caller, checkpoints
    interchange with the plain ``nn.Conv`` path byte-for-byte."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int]
    padding: Any
    mp: ModulePrecision

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel
        kernel = self.param(
            "kernel", nn.initializers.xavier_uniform(),
            (kh, kw, x.shape[-1], self.features), self.mp.param_dtype,
        )
        bias = self.param(
            "bias", nn.initializers.constant(0.2),
            (self.features,), self.mp.param_dtype,
        )
        pad = self.padding
        if not isinstance(pad, str):
            pad = tuple(tuple(p) for p in pad)
        y = jax.lax.conv_general_dilated(
            x.astype(self.mp.compute_dtype),
            kernel.astype(self.mp.compute_dtype),
            window_strides=self.strides,
            padding=pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            precision=self.mp.precision,
        )
        return y, bias


class ConvBlock(nn.Module):
    """Conv + bias + ReLU, Caffe-style 'xavier' init (def.prototxt:98-110).

    ``use_bn=True`` switches to conv (no bias) + BatchNorm + ReLU — the
    Inception-BN recipe.  A BN-free Inception-v1 from random init
    collapses (all embeddings align; the original needed aux classifiers
    + ImageNet schedules), so the BN variant is what trains from scratch.

    ``policy`` (models.precision.PrecisionPolicy) resolves this module's
    param/compute dtypes and MXU matmul precision by regex over its own
    flax path; with no policy the block is HLO-identical to the
    pre-policy constructors (``dtype`` compute over fp32 params, no
    explicit precision).  ``fused_epilogue`` routes bias+ReLU through
    the one-VMEM-pass Pallas kernel (ops.pallas_stem), and ``fuse_pool``
    =(window, stride) additionally folds the following SAME max-pool
    into the same pass (the caller must then skip its own pool).
    """

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.float32
    use_bn: bool = False
    policy: Optional[PrecisionPolicy] = None
    fused_epilogue: bool = False
    fuse_pool: Optional[Tuple[int, int]] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        mp = module_precision(self.policy, self.path, self.dtype)
        if self.fused_epilogue and not self.use_bn:
            from npairloss_tpu.ops.pallas_stem import (
                fused_bias_relu,
                fused_bias_relu_pool,
            )

            y, bias = _EpilogueConv(
                self.features, self.kernel, self.strides, self.padding,
                mp, name="Conv_0",
            )(x)
            if self.fuse_pool is not None:
                return fused_bias_relu_pool(y, bias, *self.fuse_pool)
            return fused_bias_relu(y, bias)
        x = nn.Conv(
            self.features,
            self.kernel,
            strides=self.strides,
            padding=self.padding,
            dtype=mp.compute_dtype,
            param_dtype=mp.param_dtype,
            precision=mp.precision,
            use_bias=not self.use_bn,
            kernel_init=nn.initializers.xavier_uniform(),
            bias_init=nn.initializers.constant(0.2),
        )(x)
        if self.use_bn:
            x = nn.BatchNorm(
                use_running_average=not train, momentum=0.9,
                dtype=mp.compute_dtype,
            )(x)
        return nn.relu(x)


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """NHWC space-to-depth: (N,H,W,C) -> (N,H/b,W/b,b*b*C).

    Pixel (bh+dh, bw+dw, c) lands in output channel (dh*b+dw)*C + c —
    the layout `conv1_kernel_to_s2d` (below) assumes.
    """
    n, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(
            f"space_to_depth needs H, W divisible by {block}, got {h}x{w} "
            "(the s2d stem requires even input dims; use the plain trunk "
            "for odd crops)"
        )
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


def conv1_kernel_to_s2d(kernel):
    """Convert a (7,7,C,F) stem kernel to its (4,4,4C,F) s2d equivalent.

    With Flax SAME padding a 7x7/s2 stem computes
    ``o[i] = sum_p W[p] x[2i + p - 2]`` (pad_lo=2).  Writing
    ``p - 2 = 2u + d`` (d in {0,1}) turns it into a 4x4/s1 conv over the
    space_to_depth(2) grid with offsets u in {-1..2} — i.e. pad (1,2) —
    where s2d channel ``(dh*2+dw)*C + c`` holds pixel parity (dh, dw).
    With kernel index u_k = u+1, source tap p = 2*u_k + d; the one slot
    with p = 7 (u_k=3, d=1) is zero.  The map is injective, so the
    conversion is lossless.  Shared by the GoogLeNet and ResNet
    ``stem_s2d`` variants.
    """
    kernel = np.asarray(kernel)
    kh, kw, cin, cout = kernel.shape
    if (kh, kw) != (7, 7):
        raise ValueError(f"expected a 7x7 stem kernel, got {kernel.shape}")
    out = np.zeros((4, 4, 4 * cin, cout), dtype=kernel.dtype)
    for u in range(4):
        for v in range(4):
            for dh in range(2):
                for dw in range(2):
                    p, q = 2 * u + dh, 2 * v + dw
                    if 0 <= p < 7 and 0 <= q < 7:
                        d = (dh * 2 + dw) * cin
                        out[u, v, d : d + cin, :] = kernel[p, q, :, :]
    return out


def max_pool(x, window=3, stride=2, padding="SAME"):
    return nn.max_pool(x, (window, window), strides=(stride, stride), padding=padding)


def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))
