"""BASELINE.json config 5 (stretch): ViT-B/16, 32k-batch N-pair
contrastive over ICI — the CLIP-scale negative pool.

Two engines, same semantics, both avoiding the dense 32k x 32k pair
matrix (4+ GB that cannot exist in HBM):

  * multi-chip: ring-blockwise pooling (``parallel.ring``) — the pair
    matrix streams over ppermute hops, each shard holding only its
    N_local x N_block tile;
  * single-chip: Pallas fused blockwise kernels
    (``blockwise_npair_loss_with_aux``) — (BN x BM) tiles through VMEM.

Run (any JAX backend; sizes scale down automatically for demo):

    python examples/vit_32k_stretch.py --batch 1024 --image 64
    python examples/vit_32k_stretch.py --batch 32768 --mode pallas  # one v5e chip

The embedding trunk is the registry ViT-B/16; for the loss-path stretch
demo the images are synthetic identity clusters.
"""

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--image", type=int, default=64)
    ap.add_argument("--mode", choices=["ring", "pallas", "auto"],
                    default="auto")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (e.g. 8 virtual devices "
                         "via XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    ap.add_argument("--mining", choices=["flagship", "absolute"],
                    default="flagship",
                    help="flagship = the shipped def.prototxt config "
                         "(GLOBAL/RELATIVE_HARD AP, streamed radix "
                         "selection); absolute = LOCAL/HARD only")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from npairloss_tpu.models import get_model, jit_init
    from npairloss_tpu.ops.npair_loss import (
        REFERENCE_CONFIG,
        MiningMethod,
        NPairLossConfig,
    )
    from npairloss_tpu.data.synthetic import synthetic_identity_batches

    if args.mining == "flagship":
        cfg = REFERENCE_CONFIG
    else:
        cfg = NPairLossConfig(
            margin_diff=-0.05, an_mining_method=MiningMethod.HARD
        )
    devices = jax.devices()
    mode = args.mode
    if mode == "auto":
        mode = "ring" if len(devices) > 1 else "pallas"
    print(f"devices={len(devices)} ({devices[0].platform}), mode={mode}")

    model = get_model("vit_b16", dtype=jnp.bfloat16)
    variables = jit_init(
        model, jax.random.PRNGKey(0),
        jnp.zeros((2, args.image, args.image, 3), jnp.float32),
    )

    batches = synthetic_identity_batches(
        args.batch // 2, args.batch // 2, 2,
        (args.image, args.image, 3), noise=0.5,
    )
    x_np, lab_np = next(batches)

    if mode == "pallas":
        from npairloss_tpu.ops.pallas_npair import (
            blockwise_npair_loss_with_aux,
        )

        @jax.jit
        def step(variables, x, lab):
            emb = model.apply(variables, x, train=False)
            loss, _ = blockwise_npair_loss_with_aux(
                emb, lab, cfg, block_size=512
            )
            return loss, jax.grad(
                lambda e: blockwise_npair_loss_with_aux(
                    e, lab, cfg, block_size=512
                )[0]
            )(emb)

        x, lab = jnp.asarray(x_np), jnp.asarray(lab_np)
        run = lambda: step(variables, x, lab)
    else:
        from jax.sharding import PartitionSpec as P

        from npairloss_tpu.parallel import data_parallel_mesh, shard_map
        from npairloss_tpu.parallel.ring import ring_npair_loss_and_metrics

        mesh = data_parallel_mesh(devices)

        def sharded(variables, x, lab):
            def per_shard(x, lab):
                emb = model.apply(variables, x, train=False)
                loss, _ = ring_npair_loss_and_metrics(
                    emb, lab, cfg, "dp", (1,)
                )
                return loss[None]

            losses = shard_map(
                per_shard, mesh=mesh,
                in_specs=(P("dp"), P("dp")), out_specs=P("dp"),
            )(x, lab)
            return losses.mean()

        step_fn = jax.jit(jax.value_and_grad(sharded, argnums=1))
        x, lab = jnp.asarray(x_np), jnp.asarray(lab_np)
        run = lambda: step_fn(variables, x, lab)

    out = run()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        out = run()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / args.steps
    loss = out[0] if isinstance(out, tuple) else out
    print(f"loss={float(jnp.asarray(loss).mean()):.4f}  "
          f"{dt * 1000:.1f} ms/step  "
          f"{args.batch / dt:.0f} embeddings/sec")


if __name__ == "__main__":
    main()
